"""A pp=2/ep=2 mixture-of-experts toy LM on the unified 4D mesh
(parallel/unified.py): pipeline stages AND experts are just SHARDINGS
inside ShardedTrainStep's single donated launch — no eager
pipeline/MoE dispatch, and every platform feature (ZeRO, AOT warmup,
elastic reshard, checkpoint shards) applies unchanged.

Run (single host — 8 virtual CPU devices):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_moe_lm.py --steps 20

Scale to N hosts by changing ONLY the launch line (the script reads the
exported mesh env, zero code changes):

    python tools/launch.py -n 16 --launcher ssh -H hosts \
        --mesh 16,1,2,2 --mesh-axes dp,tp,pp,ep --zero-stage 2 \
        python examples/train_moe_lm.py --steps 1000

The model is PipelineMoEBlock: in_units -> D, two pipeline stages
(dense + Switch-MoE FFN each, stage params stacked (S, ...) sharded
P(pp), expert params (S, E, ...) sharded P(pp, ep)), D -> classes head.
The microbatched schedule runs as masked ticks INSIDE the step program,
so launches/step stays 1.0 — watch it (and the per-expert router load)
with --telemetry + tools/mxt_top.py.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, parallel, profiler


def pick_mesh(spec=None):
    """The launch-line mesh when tools/launch.py exported one, else a
    local dp×tp×pp×ep mesh sized to the visible devices (pp/ep collapse
    to 1 when there are too few devices — same program, fewer axes)."""
    if spec:
        shape = tuple(int(s) for s in spec.split(","))
        return parallel.make_mesh(shape, ("dp", "tp", "pp", "ep"))
    if os.environ.get("MXT_MESH_SHAPE"):
        return parallel.make_mesh()  # no-arg: the launch-line mesh
    import jax

    n = jax.device_count()
    shape = (-1, 1, 2, 2) if n % 4 == 0 else (-1, 1, 1, 1)
    return parallel.make_mesh(shape, ("dp", "tp", "pp", "ep"))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--stages", type=int, default=2)
    ap.add_argument("--experts", type=int, default=2)
    ap.add_argument("--microbatches", type=int, default=4)
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument("--mesh", default=None,
                    help="local mesh shape override, e.g. 2,1,2,2 "
                         "(axes dp,tp,pp,ep); default: launch-line "
                         "mesh, else auto-sized to visible devices")
    ap.add_argument("--zero-stage", type=int, default=None,
                    choices=(0, 1, 2, 3))
    ap.add_argument("--telemetry", action="store_true")
    ap.add_argument("--health", action="store_true",
                    help="arm the training-health plane: per-layer "
                         "stats ride the sharded step's inflight "
                         "window, and the MoE router gauges join the "
                         "default rules engine (moe_router_drop_burn "
                         "breaches while the router drops tokens)")
    args = ap.parse_args()

    if args.health:
        # before ShardedTrainStep builds: the stat row compiles into
        # the one sharded launch (MXT_HEALTH=1 equivalent)
        os.environ["MXT_HEALTH"] = "1"
        from mxnet_tpu import health

        health.default_engine()  # seeds rules incl. MoE router burn
        print("health: armed — stats ride the sharded step window; "
              "router drops feed the moe_router_drop_burn rule")

    if args.telemetry:
        os.environ.setdefault("MXT_TELEMETRY_JSONL",
                              "moe_lm_telemetry.jsonl")
        from mxnet_tpu import telemetry

        srv = telemetry.start_http_server(
            int(os.environ.get("MXT_TELEMETRY_PORT", "9109")))
        print("telemetry: JSONL -> %s ; live console:\n"
              "  python tools/mxt_top.py --url http://127.0.0.1:%d"
              % (os.environ["MXT_TELEMETRY_JSONL"],
                 srv.server_address[1]))
    mesh = pick_mesh(args.mesh)
    print("mesh:", dict(mesh.shape))

    mx.random.seed(7)
    net = parallel.PipelineMoEBlock(
        num_stages=args.stages, num_experts=args.experts,
        in_units=args.hidden, hidden=args.hidden,
        expert_hidden=2 * args.hidden, num_classes=args.classes,
        num_microbatches=args.microbatches)
    net.initialize()
    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 1e-2}, mesh=mesh,
        rules=net.sharding_rules(mesh), zero_stage=args.zero_stage)

    rng = np.random.RandomState(0)
    x = nd.array(rng.uniform(-1, 1, (args.batch_size, args.hidden))
                 .astype(np.float32))
    y = nd.array(rng.randint(0, args.classes, (args.batch_size,))
                 .astype(np.float32))
    loss = None
    for i in range(args.steps):
        loss = step(x, y)
        if (i + 1) % 10 == 0 or i + 1 == args.steps:
            print("step %d  loss %.4f"
                  % (i + 1, float(loss.asscalar())))
            if args.health:
                # land the router counters and take a rules sample so
                # the burn/trend rules have history by the final report
                from mxnet_tpu import health

                parallel.publish_moe_telemetry(net)
                health.evaluate_rules()
    # one quiet step with no host reads in between: the whole pipeline
    # schedule + MoE dispatch + loss + backward + update is ONE launch
    n0 = profiler.launch_count()
    loss = step(x, y)
    launches = profiler.launch_count() - n0
    loss.wait_to_read()
    moe = parallel.publish_moe_telemetry(net)
    print("launches/step: %d" % launches)
    print("expert load: %s  router drops: %.0f"
          % (moe["expert_load"], moe["drops"]))
    b = step.per_device_bytes()
    print("per-device bytes: params %d  opt %d"
          % (b["param_bytes"], b["opt_state_bytes"]))
    assert launches == 1, "pipeline+MoE step must stay one launch"

    if args.health:
        from mxnet_tpu import health

        # the publish above landed the router gauges in the registry —
        # the rules engine now sees them alongside the training stats
        for v in health.evaluate_rules():
            if v["ok"] is None:
                continue  # no data yet for this rule's metric
            print("health rule %-22s %s  (%s)"
                  % (v["rule"], "ok" if v["ok"] else "BREACHED",
                     v.get("detail") or v.get("description", "")))
        hp = health.render_health()
        print("health: %s — loss ema %s, %d anomaly kind(s)"
              % (hp["status"], hp.get("loss_ema"),
                 len(hp.get("anomalies") or ())))


if __name__ == "__main__":
    main()
