"""MLP on MNIST via the symbolic Module API (ref:
example/image-classification/train_mnist.py --network mlp). The whole
bound graph lowers to one XLA program; Module.fit drives epochs,
metrics, and checkpoints exactly like the reference loop.

Run:  python examples/train_mnist_module.py --epochs 2
"""
import argparse

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


import numpy as np

import mxnet_tpu as mx


def mlp_symbol():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
    h = mx.sym.Activation(h, act_type="relu", name="relu1")
    h = mx.sym.FullyConnected(h, num_hidden=64, name="fc2")
    h = mx.sym.Activation(h, act_type="relu", name="relu2")
    h = mx.sym.FullyConnected(h, num_hidden=10, name="fc3")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--checkpoint-prefix", default=None)
    args = p.parse_args()

    # learnable synthetic digits (class prototypes + noise) so the
    # reported accuracy is a convergence signal, not 10% noise
    rng = np.random.RandomState(0)
    protos = rng.rand(10, 784).astype("f4")
    y = rng.randint(0, 10, (4096,))
    x = (protos[y] + rng.normal(0, 0.35, (4096, 784))).astype("f4")
    y = y.astype("f4")
    train_iter = mx.io.NDArrayIter(x, y, args.batch_size, shuffle=True,
                                   label_name="softmax_label")
    val_iter = mx.io.NDArrayIter(x[:512], y[:512], args.batch_size,
                                 label_name="softmax_label")

    mod = mx.mod.Module(mlp_symbol(), data_names=("data",),
                        label_names=("softmax_label",))
    callbacks = [mx.callback.Speedometer(args.batch_size, frequent=10)]
    epoch_cbs = []
    if args.checkpoint_prefix:
        epoch_cbs.append(mx.callback.module_checkpoint(
            mod, args.checkpoint_prefix))
    mod.fit(train_iter, eval_data=val_iter,
            optimizer="sgd", initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            eval_metric="acc",
            batch_end_callback=callbacks,
            epoch_end_callback=epoch_cbs or None,
            num_epoch=args.epochs)
    score = mod.score(val_iter, mx.metric.Accuracy())
    print("final val:", score)


if __name__ == "__main__":
    main()
