"""Long-context GPT with sequence parallelism — zero model changes.

The flagship long-context flow: the stock model-zoo GPT runs with its
attention sequence-sharded over a mesh via `parallel.sequence_scope` —
each device holds T/n of the sequence and KV blocks rotate around the
ring (ICI neighbor traffic on real TPU hardware; virtual CPU devices
here). Memory per device for attention state drops O(T) -> O(T/n).

Run:  XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
      python examples/long_context_gpt.py --devices 8 --seq-len 1024
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=8,
                   help="sequence shards (virtual CPU devices here)")
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--batch-size", type=int, default=2)
    p.add_argument("--steps", type=int, default=3)
    p.add_argument("--tpu", action="store_true",
                   help="run on the TPU backend (default: CPU mesh — "
                        "probing a wedged tunnel can hang)")
    args = p.parse_args()

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=%d"
            % args.devices).strip()

    import jax

    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, parallel
    from mxnet_tpu.gluon.model_zoo.gpt import gpt_mini

    assert args.seq_len % args.devices == 0, \
        "seq-len must divide by the shard count"

    mx.random.seed(0)
    net = gpt_mini(dropout=0.0, max_length=args.seq_len)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-4})
    loss_fn = gluon.loss.SoftmaxCELoss()

    mesh = parallel.make_mesh(
        (args.devices,), ("sp",),
        devices=jax.devices()[:args.devices])

    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randint(
        0, 1000, (args.batch_size, args.seq_len)).astype(np.float32))
    y = mx.nd.array(np.roll(x.asnumpy(), -1, axis=1))

    print("T=%d over %d sequence shards (T/n = %d per device)"
          % (args.seq_len, args.devices,
             args.seq_len // args.devices))
    with parallel.sequence_scope(mesh, "sp"):
        for step in range(args.steps):
            tic = time.time()
            with autograd.record():
                logits = net(x)  # stock model — attention rides the ring
                loss = loss_fn(
                    logits.reshape((-1, logits.shape[-1])),
                    y.reshape((-1,)))
            loss.backward()
            trainer.step(args.batch_size)
            print("step %d: loss %.4f (%.2fs)"
                  % (step, float(loss.mean().asnumpy()),
                     time.time() - tic))


if __name__ == "__main__":
    main()
