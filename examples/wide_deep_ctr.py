"""Wide&Deep CTR with row_sparse embedding gradients (ref:
example/sparse/wide_deep/train.py). sparse_grad=True keeps the wide
tower's huge embedding update sparse at the framework boundary (the
jitted step keeps XLA-friendly dense scatter-adds — see sparse.py's
design note). Synthetic clicks keep it runnable anywhere.

Run:  python examples/wide_deep_ctr.py --iters 20
"""
import argparse

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import model_zoo


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=1024)
    p.add_argument("--wide-vocab", type=int, default=100000)
    p.add_argument("--deep-vocab", type=int, default=10000)
    args = p.parse_args()

    mx.random.seed(0)
    net = model_zoo.wide_deep(
        wide_vocab=args.wide_vocab, deep_vocab=args.deep_vocab,
        embed_dim=16, hidden=(64, 32), classes=2, sparse_grad=True)
    net.initialize()

    rng = np.random.RandomState(0)
    n_wide, n_deep = 8, 4
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = mx.gluon.Trainer(net.collect_params(), "adam",
                               {"learning_rate": 1e-3})
    metric = mx.metric.Accuracy()
    for i in range(args.iters):
        xw = nd.array(rng.randint(0, args.wide_vocab,
                                  (args.batch_size, n_wide)).astype("f4"))
        xd = nd.array(rng.randint(0, args.deep_vocab,
                                  (args.batch_size, n_deep)).astype("f4"))
        y = nd.array(rng.randint(0, 2, (args.batch_size,)).astype("f4"))
        with mx.autograd.record():
            out = net(xw, xd)
            loss = loss_fn(out, y).mean()
        loss.backward()
        trainer.step(1)
        metric.update([y], [out])
        if (i + 1) % 5 == 0:
            print("iter %d loss %.4f acc %.4f"
                  % (i + 1, float(loss.asnumpy()), metric.get()[1]))


if __name__ == "__main__":
    main()
