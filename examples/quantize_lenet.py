"""Post-training int8 quantization of a trained LeNet (ref:
example/quantization/imagenet_gen_qsym_onedrive.py, shrunk to a
synthetic task): train fp32 with Module.fit, calibrate + quantize with
mx.contrib.quantization.quantize_model, compare accuracies, and save the
deployable int8 pair (prefix-symbol.json + prefix-0000.params — the
reference binary format, loadable by Module or SymbolBlock).

Run:  python examples/quantize_lenet.py --epochs 3 --calib-mode naive
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx


def lenet_symbol(classes):
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8,
                             pad=(1, 1), name="conv1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max", name="pool1")
    net = mx.sym.Convolution(net, kernel=(3, 3), num_filter=16,
                             pad=(1, 1), name="conv2")
    net = mx.sym.Activation(net, act_type="relu", name="relu2")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max", name="pool2")
    net = mx.sym.Flatten(net, name="flat")
    net = mx.sym.FullyConnected(net, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu3")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def synth_data(n, img=12, classes=4, noise=0.3, seed=0):
    """Orthogonal smooth prototypes + noise — learnable and separable."""
    coarse = np.linalg.qr(np.random.RandomState(0).randn(9, 9))[0][:classes]
    protos = np.stack([
        np.kron(c.reshape(3, 3) * 3.0,
                np.ones((img // 3 + 1, img // 3 + 1)))[:img, :img]
        for c in coarse])
    r = np.random.RandomState(seed)
    y = r.randint(0, classes, n)
    x = protos[y] + noise * r.randn(n, img, img)
    return x[:, None].astype(np.float32), y.astype(np.float32)


def accuracy(symbol, arg, aux, X, y, batch):
    batch = min(batch, len(X))  # whole-set batches still evaluate
    mod = mx.module.Module(symbol, data_names=["data"], label_names=None)
    mod.bind(data_shapes=[("data", (batch,) + X.shape[1:])],
             for_training=False)
    mod.set_params(arg, aux)
    hit = tot = 0
    for i in range(0, len(X) - batch + 1, batch):
        mod.forward(mx.io.DataBatch(
            data=[mx.nd.array(X[i:i + batch])], label=None),
            is_train=False)
        pred = mod.get_outputs()[0].asnumpy().argmax(axis=1)
        hit += int((pred == y[i:i + batch]).sum())
        tot += batch
    return hit / tot


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--train-size", type=int, default=768)
    ap.add_argument("--calib-mode", default="naive",
                    choices=["none", "naive", "entropy"])
    ap.add_argument("--out-prefix", default="lenet_int8")
    args = ap.parse_args()

    mx.random.seed(7)
    Xt, yt = synth_data(args.train_size, seed=1)
    Xv, yv = synth_data(512, seed=2)
    train = mx.io.NDArrayIter(Xt, yt, batch_size=args.batch_size,
                              shuffle=True, label_name="softmax_label")

    mod = mx.module.Module(lenet_symbol(4), data_names=["data"],
                           label_names=["softmax_label"])
    mod.fit(train, num_epoch=args.epochs, optimizer="adam",
            optimizer_params={"learning_rate": 2e-3}, eval_metric="acc")
    arg, aux = mod.get_params()
    symbol = mod.symbol

    calib = mx.io.NDArrayIter(Xv[:256], yv[:256],
                              batch_size=args.batch_size,
                              label_name="softmax_label")
    qsym, qarg, qaux = mx.contrib.quantization.quantize_model(
        symbol, arg, aux, calib_mode=args.calib_mode,
        calib_data=None if args.calib_mode == "none" else calib,
        num_calib_examples=256)

    acc_f = accuracy(symbol, arg, aux, Xv, yv, args.batch_size)
    acc_q = accuracy(qsym, qarg, qaux, Xv, yv, args.batch_size)
    print("fp32 val acc %.4f" % acc_f)
    print("int8 val acc %.4f (calib=%s, delta %.4f)"
          % (acc_q, args.calib_mode, acc_f - acc_q))

    mx.model.save_checkpoint(args.out_prefix, 0, qsym, qarg, qaux)
    print("saved %s-symbol.json + %s-0000.params (int8, reference "
          "binary format)" % (args.out_prefix, args.out_prefix))


if __name__ == "__main__":
    main()
