"""ResNet-50 data-parallel training over an ImageRecordIter shard (ref:
example/image-classification/train_imagenet.py). Demonstrates the
TPU-native data-parallel path: the whole train step (fwd, bwd, fused
optimizer) is ONE jitted XLA program over a device mesh, with the batch
sharded along the data axis; the native C++ record engine feeds the
decode workers when available.

Without a real shard this still runs: --synthetic generates a small
RecordIO file of random JPEGs first.

Run:  python examples/train_imagenet_resnet.py --synthetic --iters 10
"""
import argparse

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, parallel, recordio
from mxnet_tpu.gluon import model_zoo, nn


def make_synthetic_shard(path, n=256, hw=96):
    rng = np.random.RandomState(0)
    w = recordio.MXRecordIO(path, "w")
    for i in range(n):
        img = rng.randint(0, 255, (hw, hw, 3), dtype=np.uint8)
        w.write(recordio.pack_img((0, float(i % 10), i, 0), img,
                                  img_fmt=".png"))
    w.close()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--rec", default="data/train.rec")
    p.add_argument("--synthetic", action="store_true")
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--image-shape", default="3,64,64")
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--classes", type=int, default=10)
    args = p.parse_args()

    if args.synthetic and not os.path.exists(args.rec):
        os.makedirs(os.path.dirname(args.rec) or ".", exist_ok=True)
        make_synthetic_shard(args.rec)

    shape = tuple(int(s) for s in args.image_shape.split(","))
    it = mx.io.ImageRecordIter(
        path_imgrec=args.rec, data_shape=shape,
        batch_size=args.batch_size, shuffle=True, rand_mirror=True,
        preprocess_threads=4, layout="NHWC")  # feed MXU-native batches

    mx.random.seed(0)
    # channels-last is the MXU-native layout
    with nn.layout_scope("NHWC"):
        net = model_zoo.get_model("resnet50_v1", classes=args.classes)
    net.initialize(init=mx.init.Xavier())
    if args.dtype == "bfloat16":
        net.cast("bfloat16")

    c, h, w = shape
    net(nd.zeros((args.batch_size, h, w, c), dtype=args.dtype))
    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": args.lr, "momentum": 0.9})

    speedo = mx.callback.Speedometer(args.batch_size, frequent=5)
    n = 0
    for epoch in range(100):
        it.reset()
        for batch in it:
            # iterator already emits NHWC — no layout flip anywhere
            x = batch.data[0].astype(args.dtype)
            loss = step(x, batch.label[0])
            n += 1
            speedo(mx.model.BatchEndParam(epoch=epoch, nbatch=n,
                                          eval_metric=None, locals=None))
            if n >= args.iters:
                loss.wait_to_read()
                print("done: loss %.4f after %d iters"
                      % (float(loss.asnumpy()), n))
                return


if __name__ == "__main__":
    main()
