"""ResNet-50 data-parallel training over an ImageRecordIter shard (ref:
example/image-classification/train_imagenet.py). Demonstrates the
TPU-native data-parallel path: the whole train step (fwd, bwd, fused
optimizer) is ONE jitted XLA program over a device mesh, with the batch
sharded along the data axis; the native C++ record engine feeds the
decode workers when available.

``--streaming-input`` swaps the per-process iterator for the pod-scale
streaming data plane (mxnet_tpu/data_plane/): the shard's records are
chunk-leased to a per-host decode-worker fleet (``MXT_DATA_WORKERS``),
partitioned across hosts from the launch-line topology with cross-host
work stealing, and the consumer's wait time is stamped as the per-host
``data_wait`` phase — add ``--telemetry`` and point
``python tools/mxt_top.py --jsonl imagenet_telemetry.jsonl --once`` at
it to see the per-host data rec/s + data_wait attribution live.

Without a real shard this still runs: --synthetic generates a small
indexed RecordIO file of random JPEGs first.

Run:  python examples/train_imagenet_resnet.py --synthetic --iters 10
      python examples/train_imagenet_resnet.py --synthetic --iters 10 \
          --streaming-input --telemetry
"""
import argparse

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import data_plane, nd, parallel, recordio
from mxnet_tpu.gluon import model_zoo, nn


def make_synthetic_shard(path, n=256, hw=96):
    """Indexed shard (the .idx sidecar is what lets the data plane's
    chunks seek mid-shard; ImageRecordIter ignores it happily)."""
    rng = np.random.RandomState(0)
    idx = os.path.splitext(path)[0] + ".idx"
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(n):
        img = rng.randint(0, 255, (hw, hw, 3), dtype=np.uint8)
        w.write_idx(i, recordio.pack_img((0, float(i % 10), i, 0), img,
                                         img_fmt=".png"))
    w.close()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--rec", default="data/train.rec")
    p.add_argument("--synthetic", action="store_true")
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--image-shape", default="3,64,64")
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--classes", type=int, default=10)
    p.add_argument("--streaming-input", action="store_true",
                   help="feed through the streaming data plane "
                        "(chunk-leased decode fleet + work stealing) "
                        "instead of the per-process ImageRecordIter")
    p.add_argument("--data-workers", type=int, default=None,
                   help="decode workers per host (MXT_DATA_WORKERS)")
    p.add_argument("--telemetry", action="store_true",
                   help="write telemetry JSONL "
                        "(imagenet_telemetry.jsonl) for tools/mxt_top.py "
                        "— the data section shows per-host rec/s, queue "
                        "depth, steals, and data_wait share")
    args = p.parse_args()

    if args.telemetry:
        os.environ.setdefault("MXT_TELEMETRY_JSONL",
                              "imagenet_telemetry.jsonl")

    if args.synthetic and not os.path.exists(args.rec):
        os.makedirs(os.path.dirname(args.rec) or ".", exist_ok=True)
        make_synthetic_shard(args.rec)

    shape = tuple(int(s) for s in args.image_shape.split(","))

    def batches():
        """Yield (x, y) NDArray pairs, epoch after epoch."""
        if args.streaming_input:
            # topology from the launch line (MXT_WORKER_ID /
            # MXT_NUM_WORKERS — exported by tools/launch.py); one host
            # here unless launched distributed
            manifest = data_plane.ShardManifest([args.rec])
            decoder = data_plane.ImageDecoder(
                shape, rand_crop=True, rand_mirror=True, layout="NHWC")
            loader = data_plane.StreamingDataLoader(
                manifest, args.batch_size, decoder,
                num_workers=args.data_workers, prefetch_to_device=True)
            while True:
                for b in loader:
                    # short tail batches would retrace the fused step
                    if b.data.shape[0] == args.batch_size:
                        yield b.data, b.label
        else:
            it = mx.io.ImageRecordIter(
                path_imgrec=args.rec, data_shape=shape,
                batch_size=args.batch_size, shuffle=True,
                rand_mirror=True, preprocess_threads=4,
                layout="NHWC")  # feed MXU-native batches
            while True:
                it.reset()
                for batch in it:
                    yield batch.data[0], batch.label[0]

    mx.random.seed(0)
    # channels-last is the MXU-native layout
    with nn.layout_scope("NHWC"):
        net = model_zoo.get_model("resnet50_v1", classes=args.classes)
    net.initialize(init=mx.init.Xavier())
    if args.dtype == "bfloat16":
        net.cast("bfloat16")

    c, h, w = shape
    net(nd.zeros((args.batch_size, h, w, c), dtype=args.dtype))
    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": args.lr, "momentum": 0.9})

    speedo = mx.callback.Speedometer(args.batch_size, frequent=5)
    n = 0
    for x, y in batches():
        # iterator already emits NHWC — no layout flip anywhere
        loss = step(x.astype(args.dtype), y)
        n += 1
        speedo(mx.model.BatchEndParam(epoch=0, nbatch=n,
                                      eval_metric=None, locals=None))
        if n >= args.iters:
            loss.wait_to_read()
            print("done: loss %.4f after %d iters"
                  % (float(loss.asnumpy()), n))
            break
    if args.telemetry:
        mx.telemetry.flush(write_metrics=True)
        print("telemetry: python tools/mxt_top.py --jsonl "
              "imagenet_telemetry.jsonl --once")


if __name__ == "__main__":
    main()
