"""Serve a BERT-small-sized decoder with the continuous-batching stack.

The serving demo (ROADMAP direction 1): synthetic mixed-length requests
stream through `serving.ContinuousBatcher` over the paged-KV decode
engine — prefill buckets, page-table growth, per-request deadlines,
batch recomposition every step, and zero per-step host syncs (tokens
retire through the async engine's InflightWindow).

The model is `serving.TinyDecoder` at bert_3_64_2 scale (3 layers,
64 wide, 2 heads) — the pure-JAX decode adapter the engine consumes;
swapping in a real checkpoint means providing the same five functions
(see serving/model.py's module docstring).

Run::

    JAX_PLATFORMS=cpu python examples/serve_bert.py
    python examples/serve_bert.py --requests 64 --slots 16 --ab

`--ab` also runs the static-batching baseline (admission only at batch
boundaries) on the same traffic, the throughput case for continuous
batching. `--telemetry PATH` writes the JSONL event stream mxt_top can
tail live: `python tools/mxt_top.py --jsonl PATH`.

`--replicas N` serves the traffic through an N-replica fault-tolerant
fleet instead (membership-backed pool + SLO-aware router: load-aware
dispatch, hedged retries, failover with idempotency tokens), and
`--kill-one` SIGKILL-emulates one replica mid-run to demonstrate that
every accepted request still completes (failover, zero lost)::

    python examples/serve_bert.py --replicas 2 --kill-one

`--draft-k K` turns on speculative decoding (a 1-layer truncated draft
proposes K tokens per slot; the target verifies them in one wide
launch — greedy token-exact, more tokens per launch) and
`--quantize-kv` serves from int8 KV pages (~4x the resident sequences
per byte of pool). Both compose with every other flag::

    python examples/serve_bert.py --draft-k 4 --quantize-kv --ab
    python examples/serve_bert.py --draft-k 4 --replicas 2 --kill-one

`--prefix-cache` turns on shared-prefix KV reuse (prompts get a common
system preamble; repeat admissions enter the cached pages by reference
and prefill only their suffix — watch the hit ratio and copy-on-write
count it prints), and `--prefill-replicas N` splits an N+M fleet into
prefill/decode tiers: long prompts prefill on the prefill tier and
their KV pages ship over the transport to a decode replica
(`srv_ship_pages`/`srv_adopt_pages`)::

    python examples/serve_bert.py --prefix-cache --ab
    python examples/serve_bert.py --prefix-cache --replicas 3 \\
        --prefill-replicas 1

`--autoscale [MAX]` closes the control loop: an SLO-driven autoscaler
watches the merged fleet page (p99 vs --deadline, queue backlog,
occupancy) and grows the fleet with AOT-warm spares up to MAX
(default 4) / drains it back to the --replicas floor, printing every
scale decision. `--tenants SPEC` (e.g. ``interactive:bulk``) turns on
multi-tenant QoS: requests round-robin the named tenants, dispatch is
priority-aware (interactive preempts bulk under slot pressure), and
per-tenant quotas refuse over-quota submits typed::

    python examples/serve_bert.py --autoscale --tenants interactive:bulk
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def make_traffic(n, seed, vocab, deadline, max_new=48, system=None):
    import numpy as np

    from mxnet_tpu import serving

    rng = np.random.RandomState(seed)
    reqs = []
    for i in range(n):
        plen = int(rng.randint(4, 97))       # mixed-length prompts
        mnew = int(rng.randint(8, max(9, max_new + 1)))  # mixed budgets
        prompt = rng.randint(1, vocab, plen).tolist()
        if system is not None and i % 2:     # half share the preamble
            prompt = system + prompt
        reqs.append(serving.Request(prompt, max_new_tokens=mnew,
                                    deadline=deadline))
    return reqs


def _counter(name):
    from mxnet_tpu import telemetry

    fam = telemetry.registry().get(name)
    if fam is None:
        return 0.0
    return float(sum(ch.value for ch in fam.children().values()))


def run(batcher_cls, engine, requests, label):
    t0 = time.perf_counter()
    sched = batcher_cls(engine)
    for r in requests:
        sched.submit(r)
    done = sched.run()
    dt = time.perf_counter() - t0
    completed = [r for r in done if r.state == "completed"]
    evicted = [r for r in done if r.state == "evicted"]
    tokens = sum(len(r.output_tokens) for r in completed)
    lats = sorted(r.t_finish - r.t_submit for r in completed
                  if r.t_finish is not None)
    pick = (lambda q: lats[min(len(lats) - 1, int(q * len(lats)))]
            if lats else 0.0)
    print("%s: %d completed / %d evicted in %d decode steps, %.1fs"
          % (label, len(completed), len(evicted), sched.steps, dt))
    print("   %.0f tokens/s   request p50 %.0fms  p99 %.0fms"
          % (tokens / dt, pick(0.5) * 1e3, pick(0.99) * 1e3))
    return tokens / dt


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--requests", type=int, default=24)
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--pages", type=int, default=512)
    p.add_argument("--deadline", type=float, default=None,
                   help="per-request SLO budget in seconds (blown "
                        "requests are evicted)")
    p.add_argument("--ab", action="store_true",
                   help="also run the static-batching baseline")
    p.add_argument("--telemetry", default=None,
                   help="JSONL sink path for tools/mxt_top.py --jsonl")
    p.add_argument("--layers", type=int, default=3,
                   help="decoder layers (default: bert_3_64_2 geometry)")
    p.add_argument("--heads", type=int, default=2)
    p.add_argument("--head-dim", type=int, default=32)
    p.add_argument("--max-new", type=int, default=48,
                   help="upper bound of the random decode budgets")
    p.add_argument("--replicas", type=int, default=1,
                   help="serve through an N-replica fault-tolerant "
                        "fleet (membership pool + SLO-aware router) "
                        "instead of a single batcher")
    p.add_argument("--kill-one", action="store_true",
                   help="with --replicas >= 2: kill one replica "
                        "mid-run (no deregister, heartbeats stop) and "
                        "show every request still completing via "
                        "failover")
    p.add_argument("--fleet-top", action="store_true",
                   help="with --replicas N: run the fleet telemetry "
                        "collector (telemetry_fleet.py) alongside the "
                        "router — membership-discovered members scraped "
                        "over the async transport, merged into one "
                        "member-labeled fleet page — and render one "
                        "fleet mxt_top frame plus a request trace tree "
                        "at the end")
    p.add_argument("--draft-k", type=int, default=0, metavar="K",
                   help="speculative decoding: a 1-layer truncated "
                        "draft proposes K tokens per slot, verified in "
                        "one wide launch (token-exact; 0 = off)")
    p.add_argument("--quantize-kv", action="store_true",
                   help="serve from int8-quantized KV pages (per-row "
                        "scales; ~4x resident sequences per pool byte)")
    p.add_argument("--prefix-cache", action="store_true",
                   help="shared-prefix KV reuse: half the traffic gets "
                        "a common system preamble; repeat admissions "
                        "enter its cached pages by reference "
                        "(refcounted, copy-on-write at divergence) and "
                        "prefill only their suffix")
    p.add_argument("--prefill-replicas", type=int, default=0,
                   metavar="N",
                   help="with --replicas: run N replicas in the "
                        "PREFILL role (the rest decode) — long prompts "
                        "prefill there and their finished KV pages "
                        "ship over the transport to a decode replica "
                        "(srv_ship_pages/srv_adopt_pages)")
    p.add_argument("--autoscale", type=int, nargs="?", const=4,
                   default=None, metavar="MAX",
                   help="run the SLO-driven autoscaler over the fleet "
                        "(floor = --replicas, ceiling = MAX, default "
                        "4): the control loop watches the merged fleet "
                        "page and spawns AOT-warm spares through the "
                        "warming->routable lifecycle / drains idle "
                        "replicas; every scale decision prints at the "
                        "end")
    p.add_argument("--tenants", default=None, metavar="SPEC",
                   help="multi-tenant QoS, e.g. 'interactive:bulk' or "
                        "'interactive=0,bulk=2': requests round-robin "
                        "the named tenants, dispatch is priority-aware "
                        "(interactive preempts bulk under slot "
                        "pressure), per-tenant quotas "
                        "(MXT_TENANT_QUOTA_REQUESTS/_TOKENS) refuse "
                        "over-quota submits typed")
    p.add_argument("--watchdog", type=float, nargs="?", const=30.0,
                   default=None, metavar="SECONDS",
                   help="arm the diagnostics layer (flight recorder + "
                        "post-mortem handlers) with a hang watchdog "
                        "over the decode loop: no token retirement for "
                        "SECONDS (default 30) with work outstanding "
                        "dumps an mxt-postmortem-*.json; "
                        "MXT_WATCHDOG_ACTION=abort makes the replica "
                        "die typed so a supervisor respawns it")
    args = p.parse_args()

    if args.prefix_cache and args.draft_k:
        p.error("--prefix-cache rides the plain engine's fused "
                "suffix admission; drop --draft-k")

    if args.telemetry:
        os.environ["MXT_TELEMETRY_JSONL"] = args.telemetry

    if args.watchdog is not None:
        from mxnet_tpu import config, diagnostics

        diagnostics.enable(timeout=args.watchdog)
        print("watchdog: armed (%.0fs, action=%s); post-mortems -> %s"
              % (args.watchdog, config.get("MXT_WATCHDOG_ACTION"),
                 config.get("MXT_POSTMORTEM_DIR")))

    from mxnet_tpu import nd, serving

    # default: bert_3_64_2 geometry — 3 layers, 64 units, 2 heads
    model = serving.TinyDecoder(vocab=512, num_layers=args.layers,
                                num_heads=args.heads,
                                head_dim=args.head_dim, max_len=512)
    params = model.init_params(0)

    if args.draft_k:
        draft_model, draft_params = model.truncated(params, 1)

    def engine():
        cache = serving.PagedKVCache(model.num_layers, model.num_heads,
                                     model.head_dim,
                                     num_pages=args.pages,
                                     quantized=args.quantize_kv)
        if args.draft_k:
            eng = serving.SpeculativeEngine(
                model, draft_model, params=params,
                draft_params=draft_params, draft_k=args.draft_k,
                slots=args.slots, cache=cache,
                draft_cache=serving.PagedKVCache(
                    draft_model.num_layers, draft_model.num_heads,
                    draft_model.head_dim, num_pages=args.pages,
                    quantized=args.quantize_kv),
                prefill_buckets=(64, 128), max_context=256)
        else:
            buckets = (64, 128, 192) if args.prefix_cache \
                else (64, 128)
            eng = serving.DecodeEngine(model, params=params,
                                       slots=args.slots, cache=cache,
                                       prefill_buckets=buckets,
                                       max_context=256,
                                       prefix_cache=args.prefix_cache)
        t0 = time.perf_counter()
        n = eng.aot_warmup()
        print("aot_warmup: %d request-path programs in %.1fs "
              "(set MXT_COMPILE_CACHE_DIR to make the next replica "
              "replay them from disk)"
              % (n, time.perf_counter() - t0))
        return eng

    import numpy as np

    system = (np.random.RandomState(3).randint(1, 512, 64).tolist()
              if args.prefix_cache else None)

    if args.replicas > 1 or args.kill_one or args.fleet_top \
            or args.prefill_replicas or args.autoscale is not None \
            or args.tenants:
        n = max(2 if args.kill_one else 1, args.replicas,
                args.prefill_replicas + 1)
        roles = None
        if args.prefill_replicas:
            roles = (["prefill"] * args.prefill_replicas
                     + ["decode"] * (n - args.prefill_replicas))
            print("fleet roles: %s" % " ".join(roles))
        pool, coord = serving.local_serving_fleet(n, engine,
                                                  roles=roles)
        qos = serving.QosPolicy.parse(args.tenants) if args.tenants \
            else None
        router = serving.FleetRouter(pool, slo=args.deadline, qos=qos)
        scaler = None
        if args.autoscale is not None:
            scaler = serving.FleetAutoscaler(
                router, engine, slo=args.deadline, min_replicas=n,
                max_replicas=max(n, args.autoscale))
            print("autoscale: floor %d, ceiling %d"
                  % (n, max(n, args.autoscale)))
        collector = None
        if args.fleet_top:
            from mxnet_tpu import telemetry_fleet

            collector = telemetry_fleet.FleetCollector(server=coord)
            telemetry_fleet.set_default_collector(collector)
            collector.refresh()
            collector.start(interval=0.2)
        rng = np.random.RandomState(7)
        tenant_names = sorted(qos.tenants()) if qos is not None else []
        t0 = time.perf_counter()
        reqs = []
        over_quota = 0
        for i in range(args.requests):
            plen = int(rng.randint(4, 97))
            mnew = int(rng.randint(8, max(9, args.max_new + 1)))
            prompt = rng.randint(1, 512, plen).tolist()
            if system is not None and i % 2:
                prompt = system + prompt
            tenant = tenant_names[i % len(tenant_names)] \
                if tenant_names else None
            try:
                reqs.append(router.submit(
                    prompt, max_new_tokens=mnew,
                    deadline=args.deadline, token="req-%d" % i,
                    tenant=tenant))
            except serving.OverQuotaError as e:
                over_quota += 1
                print("over quota (tenant %s): req-%d refused typed"
                      % (e.tenant, i))
        if args.kill_one:
            while router.step() and router.steps < 8:
                pass
            victim = pool.get(n - 1)
            victim.kill()
            print("killed replica %d mid-run (no deregister — the "
                  "fleet fails its in-flight requests over)"
                  % victim.index)
        if scaler is not None:
            while router.step():
                scaler.step()
        else:
            router.run()
        dt = time.perf_counter() - t0
        done = [r for r in reqs if r.state == "completed"]
        tokens = sum(len(r.result) for r in done)
        lats = sorted(r.t_finish - r.t_submit for r in done)
        pick = (lambda q: lats[min(len(lats) - 1, int(q * len(lats)))]
                if lats else 0.0)
        print("fleet(%d): %d/%d completed, %d lost, %.1fs"
              % (n, len(done), len(reqs), len(reqs) - len(done), dt))
        print("   %.0f tokens/s   request p50 %.0fms  p99 %.0fms"
              % (tokens / dt, pick(0.5) * 1e3, pick(0.99) * 1e3))
        print("   failovers %d   hedges %d   replays %d   by replica: %s"
              % (sum(r.failovers for r in reqs),
                 sum(r.hedges for r in reqs), router.replays,
                 {h.index: sum(1 for r in done
                               if r.committed_by == h.index)
                  for h in pool.replicas()}))
        if scaler is not None:
            ups = sum(1 for d in scaler.decisions
                      if d["direction"] == "up")
            downs = sum(1 for d in scaler.decisions
                        if d["direction"] == "down")
            print("   autoscale: %d -> %d replicas (%d up, %d down)"
                  % (n, len(pool.routable()), ups, downs))
            for d in scaler.decisions:
                print("     #%d %-8s %s" % (d["seq"], d["direction"],
                                            d.get("reason")))
            scaler.close()
        if qos is not None:
            by_tenant = {}
            for r in done:
                key = r.tenant or "default"
                by_tenant[key] = by_tenant.get(key, 0) + 1
            pre = sum(r.preemptions for r in reqs)
            print("   tenants: %s   preemptions %d   over-quota "
                  "refused %d"
                  % (" ".join("%s=%d" % kv
                              for kv in sorted(by_tenant.items())),
                     pre, over_quota))
        if args.prefix_cache:
            hits = _counter("mxt_serving_prefix_hits_total")
            miss = _counter("mxt_serving_prefix_misses_total")
            print("   prefix: hit %.3f (%d/%d)   cow %d"
                  % (hits / max(1.0, hits + miss), hits, hits + miss,
                     _counter("mxt_serving_cow_copies_total")))
        if args.prefill_replicas:
            print("   handoff: %d pages shipped, %d adopted, %.1f KiB "
                  "over the wire"
                  % (_counter("mxt_serving_pages_shipped_total"),
                     _counter("mxt_serving_pages_adopted_total"),
                     _counter("mxt_serving_ship_bytes_total") / 1024))
        if collector is not None:
            from mxnet_tpu import telemetry_fleet

            collector.stop()
            collector.scrape()
            sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                            "..", "tools"))
            try:
                import mxt_top
            finally:
                sys.path.pop(0)
            samples = mxt_top.parse_prometheus(
                collector.render_prometheus())
            print("\n-- fleet mxt_top (one frame over the merged "
                  "member-labeled page; live: mxt_top --fleet "
                  "--url http://127.0.0.1:$MXT_TELEMETRY_PORT) --")
            print(mxt_top.render(samples, None, 0))
            shown = next((r for r in reqs if r.failovers or r.hedges),
                         reqs[0] if reqs else None)
            if shown is not None:
                tree = collector.trace_tree(shown.trace_id)
                print("\n-- trace %s (token %s: %s) --"
                      % (shown.trace_id, shown.token,
                         "failover" if shown.failovers else
                         ("hedged" if shown.hedges else "plain")))
                for track in sorted(tree["tracks"]):
                    print("  %-12s %s" % (track, " -> ".join(
                        s["name"] for s in tree["tracks"][track])))
                print("(Chrome trace-event JSON: GET /debug/timeline"
                      "?trace_id=%s on the telemetry endpoint, or "
                      "load it in Perfetto)" % shown.trace_id)
            collector.close()
            telemetry_fleet.set_default_collector(None)
        for h in pool.replicas():
            try:
                h.close()
            except Exception:  # noqa: BLE001 — killed handles
                pass
        coord.close()
        nd.waitall()
        return

    cont = run(serving.ContinuousBatcher, engine(),
               make_traffic(args.requests, 7, 512, args.deadline,
                            args.max_new, system=system),
               "continuous")
    if args.prefix_cache:
        hits = _counter("mxt_serving_prefix_hits_total")
        miss = _counter("mxt_serving_prefix_misses_total")
        print("prefix: hit %.3f (%d/%d)   cow %d"
              % (hits / max(1.0, hits + miss), hits, hits + miss,
                 _counter("mxt_serving_cow_copies_total")))
    if args.ab:
        stat = run(serving.StaticBatcher, engine(),
                   make_traffic(args.requests, 7, 512, args.deadline,
                                args.max_new, system=system),
                   "static    ")
        if stat:
            print("continuous batching speedup: %.2fx" % (cont / stat))
    nd.waitall()


if __name__ == "__main__":
    main()
