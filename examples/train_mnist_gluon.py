"""LeNet-5 on MNIST via Gluon (ref: example/image-classification/
train_mnist.py + gluon examples). Uses the real MNIST files if
--data-dir has them, else synthetic digits so the example always runs.

Run:  python examples/train_mnist_gluon.py --epochs 2 --batch-size 256

Demonstrates the fused train step (gluon.CachedTrainStep). Before —
one launch for the forward, one per tape node for the backward, one for
the optimizer::

    with autograd.record():
        out = net(data)
        loss = loss_fn(out, label)
    loss.backward()
    trainer.step(batch_size)

After — the WHOLE step is one donated XLA launch (identical numerics;
ineligible configs fall back to the loop above automatically)::

    step = trainer.fuse_step(net, loss_fn, return_outputs=True)
    loss, out = step(data, label, batch_size)

Pass --no-fused-step (or set MXT_FUSED_STEP=0) to run the eager loop.

Async dispatch (engine.py): the fused step never blocks on a host read
— the engine keeps up to K steps in flight and defers flag/bookkeeping
reads (bit-exact numerics; metrics accumulate on device)::

    with mx.engine.bulk(8):          # or MXT_MAX_INFLIGHT=8
        for data, label in batches:
            loss, out = step(data, label, batch_size)
            metric.update([label], [out])   # device-side running sums
    mx.nd.waitall()                  # barrier: land deferred counters
    print(metric.get())              # the ONE host read

Pass --inflight K to set the window here (0 keeps the MXT_MAX_INFLIGHT
default of 2; 1 forces synchronous per-step reads).

Telemetry (telemetry.py): --telemetry turns on the JSONL event sink and
the Prometheus endpoint, then prints how to watch the run live::

    python examples/train_mnist_gluon.py --telemetry &
    python tools/mxt_top.py --url http://127.0.0.1:9109   # live console
    # or, offline: python tools/mxt_top.py --jsonl mnist_telemetry.jsonl

The console shows steps/s, host_syncs/step (≤ 1/K when the async window
is healthy), launches/step (1.0 = fully fused), dispatch depth, and the
skipped-step counter — all without adding a single host sync to the
training loop.

Warm start (tuning/): --warmup AOT-compiles the fused step before the
first batch; with the persistent compile cache a SECOND run pays zero
JIT anywhere in the epoch loop::

    MXT_COMPILE_CACHE_DIR=/tmp/mxt_cache python examples/train_mnist_gluon.py --warmup
    MXT_COMPILE_CACHE_DIR=/tmp/mxt_cache python examples/train_mnist_gluon.py --warmup
    # second run prints: warmup: N compiles (~0.0s XLA, cache N hit / 0 miss)
"""
import argparse

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import nn


def lenet(pad_to=None):
    """Classic widths by default; ``pad_to=dp`` rounds each layer width
    up to a multiple of dp so ZeRO's dim-0 sharding applies to every
    tensor (the classic 20/50/500 widths don't divide an 8-way data
    axis, which would silently leave everything replicated)."""
    def w(units):
        if not pad_to or pad_to <= 1:
            return units
        return ((units + pad_to - 1) // pad_to) * pad_to

    net = nn.HybridSequential()
    net.add(nn.Conv2D(w(20), kernel_size=5, activation="tanh"),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(w(50), kernel_size=5, activation="tanh"),
            nn.MaxPool2D(2, 2),
            nn.Flatten(),
            nn.Dense(w(500), activation="tanh"),
            nn.Dense(10))
    return net


def load_data(args):
    try:
        from mxnet_tpu.gluon.data.vision import MNIST

        train = MNIST(root=args.data_dir, train=True)
        x = np.stack([np.asarray(im) for im, _ in train]).astype("f4")
        y = np.asarray([lbl for _, lbl in train]).astype("f4")
        x = x.reshape(-1, 1, 28, 28) / 255.0
        return x, y
    except Exception:
        print("MNIST files not found — using synthetic digits")
        # LEARNABLE synthetic task (not random labels): 10 smooth
        # prototypes + noise, so the printed accuracy is a real
        # convergence signal (mirrors tests/test_tpu_smoke.py's
        # train-tier bar)
        rng = np.random.RandomState(0)
        protos = np.repeat(np.repeat(rng.rand(10, 1, 7, 7), 4, axis=2),
                           4, axis=3).astype("f4")
        y = rng.randint(0, 10, (4096,))
        x = (protos[y] + rng.normal(0, 0.35, (4096, 1, 28, 28))
             ).astype("f4")
        return x, y.astype("f4")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--data-dir", default="data/mnist")
    p.add_argument("--no-hybridize", dest="hybridize",
                   action="store_false", default=True,
                   help="run the eager (non-jitted) path")
    p.add_argument("--no-fused-step", dest="fused_step",
                   action="store_false", default=True,
                   help="use the eager record/backward/step loop instead "
                        "of the one-launch fused train step")
    p.add_argument("--inflight", type=int, default=0,
                   help="async dispatch window depth K (engine.bulk): the "
                        "host runs up to K fused steps ahead, deferring "
                        "host reads; 0 = MXT_MAX_INFLIGHT default, "
                        "1 = synchronous")
    p.add_argument("--telemetry", action="store_true",
                   help="write telemetry JSONL (mnist_telemetry.jsonl), "
                        "serve Prometheus metrics on 127.0.0.1:9109, and "
                        "print the tools/mxt_top.py invocation to watch "
                        "the run live")
    p.add_argument("--health", action="store_true",
                   help="arm the training-health plane (health.py): "
                        "per-layer grad/param norms + update ratios + "
                        "loss stats computed INSIDE the fused step, "
                        "anomaly detectors at window retirement, and "
                        "the default SLO rules — zero extra host "
                        "syncs per step")
    p.add_argument("--warmup", action="store_true",
                   help="AOT-compile the fused step before the first "
                        "batch (tuning.warmup). With MXT_COMPILE_CACHE_DIR "
                        "set, a second run replays every compile from the "
                        "persistent cache — zero JIT in the epoch loop")
    p.add_argument("--sharded", action="store_true",
                   help="train under parallel.ShardedTrainStep on a "
                        "device mesh (GSPMD data parallel; honors "
                        "MXT_MESH_SHAPE from tools/launch.py --mesh). "
                        "The batch size must divide the data axis")
    p.add_argument("--zero-stage", type=int, default=None,
                   choices=(0, 1, 2, 3),
                   help="with --sharded: ZeRO weight-update sharding "
                        "stage (1 shards optimizer states over the data "
                        "axis, 2 adds gradient reduce-scatter + sharded "
                        "updates, 3 shards the params FSDP-style); "
                        "default MXT_ZERO_STAGE or 0")
    p.add_argument("--watchdog", type=float, nargs="?", const=30.0,
                   default=None, metavar="SECONDS",
                   help="arm the diagnostics layer (flight recorder + "
                        "post-mortem handlers) with a hang watchdog: no "
                        "training progress for SECONDS (default 30) "
                        "dumps thread stacks + the flight-recorder tail "
                        "to an mxt-postmortem-*.json; "
                        "MXT_WATCHDOG_ACTION=abort turns a hang into a "
                        "typed, respawnable death")
    args = p.parse_args()

    if args.watchdog is not None:
        from mxnet_tpu import diagnostics

        diagnostics.enable(timeout=args.watchdog)
        print("watchdog: armed (%.0fs, action=%s); post-mortems -> %s"
              % (args.watchdog, mx.config.get("MXT_WATCHDOG_ACTION"),
                 mx.config.get("MXT_POSTMORTEM_DIR")))

    if args.telemetry:
        os.environ.setdefault("MXT_TELEMETRY_JSONL",
                              "mnist_telemetry.jsonl")
        from mxnet_tpu import telemetry

        srv = telemetry.start_http_server(
            int(os.environ.get("MXT_TELEMETRY_PORT", "9109")))
        print("telemetry: JSONL -> %s ; live console:\n"
              "  python tools/mxt_top.py --url http://127.0.0.1:%d"
              % (os.environ["MXT_TELEMETRY_JSONL"],
                 srv.server_address[1]))

    if args.health:
        # must be set BEFORE fuse_step builds: the stat row compiles
        # into the one donated step program (MXT_HEALTH=1 equivalent)
        os.environ["MXT_HEALTH"] = "1"
        from mxnet_tpu import health

        health.default_engine()  # seeds the standing rule set
        print("health: armed — per-layer stats ride the inflight "
              "window; curl /health on the telemetry port for the "
              "rules verdict")

    mx.random.seed(42)
    if args.sharded:
        import jax

        net = lenet(pad_to=len(jax.devices()))
    else:
        net = lenet()
    net.initialize(init=mx.init.Xavier())
    if args.hybridize and not args.sharded:
        net.hybridize()  # whole net -> one XLA program

    x, y = load_data(args)
    train_iter = mx.io.NDArrayIter(x, y, args.batch_size, shuffle=True)

    if args.sharded:
        # GSPMD scale-out: ONE sharded program over the mesh — the same
        # script runs 1 CPU device, the 8-device test mesh, or an
        # N-host pod (tools/launch.py --mesh 16,2 --zero-stage 2 sets
        # MXT_MESH_SHAPE/MXT_ZERO_STAGE; make_mesh() reads them)
        from mxnet_tpu import parallel

        net(nd.zeros((2, 1, 28, 28)))  # resolve deferred shapes
        mesh = parallel.make_mesh() if os.environ.get("MXT_MESH_SHAPE") \
            else parallel.make_mesh(axis_names=("data",))
        loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
        sstep = parallel.ShardedTrainStep(
            net, loss_fn, "sgd",
            {"learning_rate": args.lr, "momentum": 0.9}, mesh=mesh,
            zero_stage=args.zero_stage)
        b = sstep.per_device_bytes()
        print("sharded: mesh %s, ZeRO stage %d, per-device bytes "
              "params=%d opt=%d" % (dict(mesh.shape), sstep.zero_stage,
                                    b["param_bytes"],
                                    b["opt_state_bytes"]))
        for epoch in range(args.epochs):
            train_iter.reset()
            losses = []
            for batch in train_iter:
                loss = sstep(batch.data[0], batch.label[0])
                losses.append(loss)
            nd.waitall()
            print("epoch %d: mean loss %.4f"
                  % (epoch, float(np.mean([float(l.asscalar())
                                           for l in losses]))))
        if args.health:
            from mxnet_tpu import health

            hp = health.render_health()
            print("health: %s — loss ema %s, %d anomaly kind(s)"
                  % (hp["status"], hp.get("loss_ema"),
                     len(hp.get("anomalies") or {})))
        return

    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": args.lr, "momentum": 0.9})
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()
    speedo = mx.callback.Speedometer(args.batch_size, frequent=20)

    # forward + backward + optimizer as ONE donated XLA launch; outputs
    # ride along as extra results of the same program so the metric needs
    # no second forward
    step = trainer.fuse_step(net, loss_fn, return_outputs=True) \
        if args.fused_step else None

    if args.warmup and step is not None:
        # AOT warm-start (tuning/warmup.py): compile the whole fused
        # step from the batch signature before touching any data. With
        # MXT_COMPILE_CACHE_DIR set, run this script twice — the second
        # run's summary shows cache hits and ~0 compile seconds
        from mxnet_tpu import tuning

        x_sig = nd.zeros((args.batch_size, 1, 28, 28))
        y_sig = nd.zeros((args.batch_size,))
        step.aot_warmup(x_sig, y_sig)
        summary = tuning.warmup()
        print("warmup: %d compiles (%.2fs XLA, cache %d hit / %d miss)"
              % (summary["compiles"], summary["compile_seconds"],
                 summary["cache_hits"], summary["cache_misses"]))

    import contextlib

    # async dispatch: inside engine.bulk(K) the fused step defers its
    # host reads and Accuracy accumulates on device — the loop below
    # performs NO per-batch device->host round-trip
    window = mx.engine.bulk(args.inflight) if args.inflight \
        else contextlib.nullcontext()
    with window:
        for epoch in range(args.epochs):
            train_iter.reset()
            metric.reset()
            for i, batch in enumerate(train_iter):
                data, label = batch.data[0], batch.label[0]
                if step is not None:
                    loss, out = step(data, label, args.batch_size)
                else:
                    with autograd.record():
                        out = net(data)
                        loss = loss_fn(out, label)
                    loss.backward()
                    trainer.step(args.batch_size)
                metric.update([label], [out])
                speedo(mx.model.BatchEndParam(epoch=epoch, nbatch=i,
                                              eval_metric=metric,
                                              locals=None))
            nd.waitall()  # barrier: land deferred flags/counters
            print("epoch %d: train acc %.4f" % (epoch, metric.get()[1]))

    if args.health:
        from mxnet_tpu import health

        hp = health.render_health()
        print("health: %s — loss ema %s, %d anomaly kind(s), "
              "%d rule(s) evaluated"
              % (hp["status"], hp.get("loss_ema"),
                 len(hp.get("anomalies") or ()),
                 len(hp.get("rules") or ())))


if __name__ == "__main__":
    main()
