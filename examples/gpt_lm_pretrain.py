"""Causal-LM pretraining step for the GPT zoo model — next-token loss
over synthetic token streams, one jitted SPMD step, optional Megatron
tensor parallelism via --tp (model_zoo.gpt.tensor_parallel_rules).

Run:  python examples/gpt_lm_pretrain.py --iters 5
      python examples/gpt_lm_pretrain.py --tp 2   # ("data","model") mesh
"""
import argparse

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, parallel
from mxnet_tpu.gluon import model_zoo


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="gpt_mini",
                   choices=["gpt_mini", "gpt_small"])
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--iters", type=int, default=5)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel ways (mesh ('data','model'))")
    p.add_argument("--dtype", default="float32")
    args = p.parse_args()

    mx.random.seed(0)
    net = getattr(model_zoo, args.model)(dropout=0.0,
                                         max_length=args.seq_len)
    vocab = net._vocab_size
    net.initialize()
    if args.dtype == "bfloat16":
        net.cast("bfloat16")

    rng = np.random.RandomState(0)
    x = nd.array(rng.randint(0, vocab, (args.batch_size, args.seq_len))
                 .astype("f4"))
    y = nd.array(np.roll(x.asnumpy(), -1, axis=1))
    net(x)

    # SoftmaxCrossEntropyLoss picks along the last axis, so (B,T,V)
    # logits with (B,T) labels need no reshape wrapper
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()

    if args.tp > 1:
        mesh = parallel.make_mesh((-1, args.tp), ("data", "model"))
        rules = model_zoo.gpt.tensor_parallel_rules()
    else:
        mesh, rules = None, None
    step = parallel.ShardedTrainStep(net, loss_fn, "adam",
                                     {"learning_rate": args.lr},
                                     mesh=mesh, rules=rules)

    for i in range(args.iters):
        loss = step(x, y)
        print("iter %d loss %.4f" % (i, float(loss.asnumpy())))


if __name__ == "__main__":
    main()
