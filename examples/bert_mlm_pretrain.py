"""BERT masked-LM pretraining step (ref: the reference ecosystem's
gluon-nlp BERT pretraining entry; model: gluon/model_zoo/bert.py). The
attention uses the Pallas flash kernel on TPU; the train step is one
jitted SPMD program. Synthetic token streams keep it runnable anywhere.

Run:  python examples/bert_mlm_pretrain.py --model bert_3_64_2 --iters 5
"""
import argparse

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, parallel
from mxnet_tpu.gluon import Block, model_zoo


class MLMNet(Block):
    """Token ids in -> vocab scores out (tied decoder)."""

    def __init__(self, bert):
        super().__init__(prefix="mlm_")
        with self.name_scope():
            self.bert = bert

    def forward(self, x):
        seq, _ = self.bert(x, nd.zeros_like(x))
        return self.bert.decode_mlm(seq)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="bert_3_64_2",
                   choices=["bert_3_64_2", "bert_12_768_12",
                            "bert_24_1024_16"])
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--iters", type=int, default=5)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--dtype", default="float32")
    args = p.parse_args()

    mx.random.seed(0)
    bert = getattr(model_zoo.bert, args.model)(
        use_classifier=False, dropout=0.0, max_length=args.seq_len)
    vocab = bert._vocab_size if hasattr(bert, "_vocab_size") else 30522

    net = MLMNet(bert)
    net.initialize()
    if args.dtype == "bfloat16":
        net.cast("bfloat16")

    rng = np.random.RandomState(0)
    x = nd.array(rng.randint(0, vocab, (args.batch_size, args.seq_len))
                 .astype("f4"))
    y = nd.array(rng.randint(0, vocab, (args.batch_size, args.seq_len))
                 .astype("f4"))
    net(x)
    step = parallel.ShardedTrainStep(
        net, mx.gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": args.lr})

    for i in range(args.iters):
        loss = step(x, y)
        print("iter %d loss %.4f" % (i, float(loss.asnumpy())))


if __name__ == "__main__":
    main()
