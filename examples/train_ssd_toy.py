"""Toy single-scale SSD (ref: example/ssd): conv backbone -> per-anchor
class + box heads, MultiBoxPrior anchors, MultiBoxTarget training
targets, SmoothL1 + softmax losses, MultiBoxDetection decode at eval.
Synthetic scenes (one bright square per image) keep it runnable
anywhere; the model learns to localize the square.

Run:  python examples/train_ssd_toy.py
"""
import argparse

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import Block, nn


class ToySSD(Block):
    """8x8 feature map, A anchors per cell, one foreground class."""

    def __init__(self, num_anchors, **kwargs):
        super().__init__(**kwargs)
        self._na = num_anchors
        with self.name_scope():
            self.backbone = nn.HybridSequential(prefix="bb_")
            with self.backbone.name_scope():
                self.backbone.add(
                    nn.Conv2D(16, 3, strides=2, padding=1,
                              activation="relu"),
                    nn.Conv2D(32, 3, strides=2, padding=1,
                              activation="relu"),
                    nn.Conv2D(32, 3, strides=2, padding=1,
                              activation="relu"))
            self.cls_head = nn.Conv2D(num_anchors * 2, 3, padding=1)
            self.loc_head = nn.Conv2D(num_anchors * 4, 3, padding=1)

    def forward(self, x):
        feat = self.backbone(x)          # (B, 32, 8, 8)
        cls = self.cls_head(feat)        # (B, A*2, 8, 8)
        loc = self.loc_head(feat)        # (B, A*4, 8, 8)
        b = cls.shape[0]
        # -> (B, C=2, A_total) and (B, A_total*4), anchor-major like the
        # reference's flatten order (per-pixel, per-anchor)
        cls = cls.reshape((b, self._na, 2, -1)).transpose(
            (0, 2, 3, 1)).reshape((b, 2, -1))
        loc = loc.reshape((b, self._na, 4, -1)).transpose(
            (0, 3, 1, 2)).reshape((b, -1))
        return feat, cls, loc


def synth_batch(rng, n, size=64):
    """White squares on dark noise; label row [cls=0, corners]."""
    imgs = rng.uniform(0, 0.2, (n, 1, size, size)).astype("f4")
    labels = np.zeros((n, 1, 5), "f4")
    for i in range(n):
        s = rng.randint(size // 5, size // 3)
        x0 = rng.randint(0, size - s)
        y0 = rng.randint(0, size - s)
        imgs[i, 0, y0:y0 + s, x0:x0 + s] = 1.0
        labels[i, 0] = [0, x0 / size, y0 / size, (x0 + s) / size,
                        (y0 + s) / size]
    return nd.array(imgs), nd.array(labels)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=200)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--lr", type=float, default=0.1)
    args = p.parse_args()

    mx.random.seed(0)
    rng = np.random.RandomState(0)

    net = ToySSD(num_anchors=3)
    net.initialize(init=mx.init.Xavier())
    x0, _ = synth_batch(rng, 2)
    feat, _, _ = net(x0)
    anchors = nd.MultiBoxPrior(feat, sizes=(0.2, 0.35), ratios=(1.0, 2.0))

    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": args.lr, "momentum": 0.9})
    cls_loss = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    box_loss = mx.gluon.loss.HuberLoss()

    for i in range(args.iters):
        x, y = synth_batch(rng, args.batch_size)
        with autograd.record():
            _, cls, loc = net(x)
            loc_t, loc_m, cls_t = nd.MultiBoxTarget(anchors, y, cls)
            l_cls = cls_loss(cls.transpose((0, 2, 1)), cls_t).mean()
            l_box = box_loss(loc * loc_m, loc_t).mean()
            loss = l_cls + l_box
        loss.backward()
        trainer.step(1)
        if (i + 1) % 20 == 0:
            print("iter %d cls %.4f box %.4f" % (
                i + 1, float(l_cls.asnumpy()), float(l_box.asnumpy())))

    # detection on a fresh scene
    x, y = synth_batch(rng, 1)
    _, cls, loc = net(x)
    probs = nd.softmax(cls, axis=1)
    det = nd.MultiBoxDetection(probs, loc, anchors,
                               nms_threshold=0.45).asnumpy()
    best = det[0, 0]
    print("gt box:", y.asnumpy()[0, 0, 1:].round(2).tolist())
    print("top det: cls=%d score=%.2f box=%s"
          % (best[0], best[1], best[2:].round(2).tolist()))


if __name__ == "__main__":
    main()
