"""Wide&Deep CTR on the distributed sparse embedding parameter server
(embedding/; ref: example/sparse/wide_deep/train.py + the ps-lite
dist embedding recipe).

The embedding towers declare ``sparse_grad=True`` and the Trainer runs
with ``kvstore='dist_embedding'``: the tables shard across an embedding
server fleet by consistent hashing, each step pushes ONLY the batch's
gradient rows (applied server-side with the sparse optimizer) and pulls
ONLY those rows back through the hot-row device cache, while the dense
MLP towers keep the local fused update. Synthetic clicks keep it
runnable anywhere.

Run:
    python examples/train_wide_deep.py --iters 20
    python examples/train_wide_deep.py --embedding-servers 2 --telemetry
    # live console, in another terminal:
    #   python tools/mxt_top.py --jsonl wide_deep_telemetry.jsonl --once
"""
import argparse

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import config, nd
from mxnet_tpu.gluon import model_zoo


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=1024)
    p.add_argument("--wide-vocab", type=int, default=100000)
    p.add_argument("--deep-vocab", type=int, default=10000)
    p.add_argument("--embedding-servers", type=int, default=0,
                   help="size of the in-process sharded embedding fleet; "
                        "0 keeps the single-process local kvstore "
                        "(MXT_EMBEDDING_SERVERS connects to a running "
                        "fleet instead)")
    p.add_argument("--cache-rows", type=int, default=4096,
                   help="hot-row device cache capacity per table")
    p.add_argument("--telemetry", action="store_true",
                   help="write telemetry JSONL "
                        "(wide_deep_telemetry.jsonl) for tools/mxt_top.py")
    args = p.parse_args()

    if args.telemetry:
        os.environ.setdefault("MXT_TELEMETRY_JSONL",
                              "wide_deep_telemetry.jsonl")
    kvstore = "local"
    if args.embedding_servers > 0 or config.get("MXT_EMBEDDING_SERVERS"):
        kvstore = "dist_embedding"
        if args.embedding_servers > 0:
            config.set_default("MXT_EMBEDDING_LOCAL_SERVERS",
                               args.embedding_servers)
        config.set_default("MXT_EMBEDDING_CACHE_ROWS", args.cache_rows)

    mx.random.seed(0)
    net = model_zoo.wide_deep(
        wide_vocab=args.wide_vocab, deep_vocab=args.deep_vocab,
        embed_dim=16, hidden=(64, 32), classes=2, sparse_grad=True)
    net.initialize()

    rng = np.random.RandomState(0)
    n_wide, n_deep = 8, 4
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = mx.gluon.Trainer(net.collect_params(), "adam",
                               {"learning_rate": 1e-3}, kvstore=kvstore)
    metric = mx.metric.Accuracy()
    for i in range(args.iters):
        xw = nd.array(rng.randint(0, args.wide_vocab,
                                  (args.batch_size, n_wide)).astype("f4"))
        xd = nd.array(rng.randint(0, args.deep_vocab,
                                  (args.batch_size, n_deep)).astype("f4"))
        y = nd.array(rng.randint(0, 2, (args.batch_size,)).astype("f4"))
        with mx.autograd.record():
            out = net(xw, xd)
            loss = loss_fn(out, y).mean()
        loss.backward()
        trainer.step(args.batch_size)
        metric.update([y], [out])
        if (i + 1) % 5 == 0:
            print("iter %d loss %.4f acc %.4f"
                  % (i + 1, float(loss.asnumpy()), metric.get()[1]))
    kv = trainer._kvstore
    if kv is not None and kv.type == "dist_embedding":
        for key, tbl in kv._emb_tables.items():
            if tbl.cache is not None:
                print("table %s: cache hit ratio %.3f, %d rows resident"
                      % (key, tbl.cache.hit_ratio, len(tbl.cache)))
        kv.close()


if __name__ == "__main__":
    main()
