"""PTB-style LSTM language model with BucketingModule (ref:
example/rnn/bucketing/lstm_bucketing.py). Variable-length sentences are
bucketed; each bucket gets its own bound executor sharing one parameter
set — each executor is one compiled XLA program (the fused RNN unrolls
its recurrent scan on TPU). Synthetic corpus keeps it runnable anywhere.

Run:  python examples/lstm_ptb_bucketing.py --epochs 1
"""
import argparse

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.io import DataBatch, DataDesc


def sym_gen_factory(vocab, hidden, layers):
    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        emb = mx.sym.Embedding(data, input_dim=vocab, output_dim=hidden,
                               name="embed")
        rnn = mx.sym.RNN(mx.sym.transpose(emb, axes=(1, 0, 2)),
                         mode="lstm", state_size=hidden,
                         num_layers=layers, name="lstm")
        out = mx.sym.transpose(rnn[0], axes=(1, 0, 2))  # [0]: sequence
        pred = mx.sym.FullyConnected(
            mx.sym.reshape(out, shape=(-1, hidden)),
            num_hidden=vocab, name="pred")
        lbl = mx.sym.reshape(label, shape=(-1,))
        sm = mx.sym.SoftmaxOutput(pred, lbl, name="softmax",
                                  normalization="batch")
        return sm, ("data",), ("softmax_label",)
    return sym_gen


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batches", type=int, default=12)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--vocab", type=int, default=1000)
    args = p.parse_args()

    buckets = (8, 16, 32)
    rng = np.random.RandomState(0)
    b = args.batch_size

    mod = mx.mod.BucketingModule(
        sym_gen_factory(args.vocab, args.hidden, args.layers),
        default_bucket_key=max(buckets))
    mod.bind(data_shapes=[DataDesc("data", (b, max(buckets)))],
             label_shapes=[DataDesc("softmax_label", (b, max(buckets)))])
    # fused-RNN packed params are 1-D; Uniform handles any rank
    mod.init_params(initializer=mx.init.Uniform(0.08))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})

    per = mx.metric.Perplexity(ignore_label=None)
    for epoch in range(args.epochs):
        per.reset()
        for i in range(args.batches):
            blen = buckets[rng.randint(len(buckets))]
            x = rng.randint(1, args.vocab, (b, blen)).astype("f4")
            y = np.roll(x, -1, axis=1)
            batch = DataBatch(
                data=[nd.array(x)], label=[nd.array(y)],
                bucket_key=blen,
                provide_data=[DataDesc("data", (b, blen))],
                provide_label=[DataDesc("softmax_label", (b, blen))])
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
            per.update([nd.array(y)], [mod.get_outputs()[0]])
        print("epoch %d: %s = %.2f" % (epoch, *per.get()))


if __name__ == "__main__":
    main()
