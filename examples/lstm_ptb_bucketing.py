"""PTB-style LSTM language model with BucketingModule (ref:
example/rnn/bucketing/lstm_bucketing.py). The reference flow exactly:
sentences -> mx.rnn.BucketSentenceIter (pad into length buckets) ->
sym_gen unrolling an mx.rnn cell per bucket -> BucketingModule.fit.
Each bucket binds one executor sharing one parameter set — one compiled
XLA program per bucket (the fused RNN unrolls its recurrent scan on
TPU). Synthetic corpus keeps it runnable anywhere.

Run:  python examples/lstm_ptb_bucketing.py --epochs 1
"""
import argparse

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


import numpy as np

import mxnet_tpu as mx


def sym_gen_factory(vocab, hidden, layers, fused=True):
    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        emb = mx.sym.Embedding(data, input_dim=vocab, output_dim=hidden,
                               name="embed")
        if fused:
            cell = mx.rnn.FusedRNNCell(hidden, num_layers=layers,
                                       mode="lstm", prefix="lstm_")
        else:
            cell = mx.rnn.SequentialRNNCell()
            for i in range(layers):
                cell.add(mx.rnn.LSTMCell(hidden, prefix="lstm_l%d_" % i))
        outputs, _ = cell.unroll(seq_len, inputs=emb, layout="NTC",
                                 merge_outputs=True)
        pred = mx.sym.FullyConnected(
            mx.sym.reshape(outputs, shape=(-1, hidden)),
            num_hidden=vocab, name="pred")
        lbl = mx.sym.reshape(label, shape=(-1,))
        sm = mx.sym.SoftmaxOutput(pred, lbl, name="softmax",
                                  use_ignore=True, ignore_label=-1,
                                  normalization="valid")
        return sm, ("data",), ("softmax_label",)
    return sym_gen


def synthetic_corpus(rng, vocab, n_sentences):
    """Markov-ish sentences so perplexity has signal to minimize."""
    sentences = []
    for _ in range(n_sentences):
        ln = int(rng.choice([6, 8, 14, 16, 28, 30]))
        start = int(rng.randint(1, vocab))
        step = int(rng.randint(1, 5))
        sentences.append([(start + t * step) % (vocab - 1) + 1
                          for t in range(ln)])
    return sentences


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--sentences", type=int, default=256)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--vocab", type=int, default=1000)
    p.add_argument("--unfused", action="store_true",
                   help="stacked LSTMCells instead of the fused cell")
    args = p.parse_args()

    buckets = [8, 16, 32]
    rng = np.random.RandomState(0)
    sentences = synthetic_corpus(rng, args.vocab, args.sentences)
    data_train = mx.rnn.BucketSentenceIter(
        sentences, args.batch_size, buckets=buckets, invalid_label=-1)

    mod = mx.mod.BucketingModule(
        sym_gen_factory(args.vocab, args.hidden, args.layers,
                        fused=not args.unfused),
        default_bucket_key=data_train.default_bucket_key)
    mod.fit(data_train,
            eval_metric=mx.metric.Perplexity(ignore_label=-1),
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
            initializer=mx.init.Uniform(0.08),
            num_epoch=args.epochs,
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, frequent=8))


if __name__ == "__main__":
    main()
