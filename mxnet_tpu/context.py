"""Device context model.

Re-design of the reference Context (ref: include/mxnet/base.h — Context,
python/mxnet/context.py). Devices are JAX devices; ``tpu`` is first-class and
``gpu`` is accepted as an alias for the accelerator so reference-era scripts
run unchanged. Contexts are usable as ``with`` scopes, exactly like the
reference's ``with mx.gpu(0):`` pattern.
"""
from __future__ import annotations

import threading

from .base import MXNetError

__all__ = [
    "Context",
    "cpu",
    "gpu",
    "tpu",
    "cpu_pinned",
    "current_context",
    "num_gpus",
    "num_tpus",
]


class _CtxStack(threading.local):
    def __init__(self):
        super().__init__()
        self.stack = []


_ctx_stack = _CtxStack()


class Context:
    """A device context (device_type + device_id).

    device types mirror the reference enum (kCPU=1, kGPU=2, kCPUPinned=3,
    kCPUShared=5) plus kTPU=6 for the native accelerator. ``gpu`` resolves to
    the same physical accelerator as ``tpu`` — this build has no CUDA.
    """

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            if isinstance(device_type, str):
                if device_type not in Context.devstr2type:
                    raise MXNetError("unknown device type %r" % (device_type,))
                self.device_typeid = Context.devstr2type[device_type]
            else:
                self.device_typeid = int(device_type)
            self.device_id = int(device_id)

    @property
    def device_type(self):
        return Context.devtype2str[self.device_typeid]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    def __str__(self):
        return repr(self)

    def __enter__(self):
        _ctx_stack.stack.append(self)
        return self

    def __exit__(self, *args):
        _ctx_stack.stack.pop()

    # -- JAX resolution ----------------------------------------------------
    @property
    def jax_device(self):
        """Resolve this context to a concrete jax.Device. Under
        jax.distributed, contexts index this process's LOCAL devices
        (ref: a Context is per-worker; global placement is the mesh's
        job) — jax.devices() lists remote devices a process cannot
        address directly."""
        import jax

        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            try:
                devs = jax.local_devices(backend="cpu")
            except RuntimeError:
                devs = jax.local_devices()
        else:  # tpu / gpu → default accelerator backend
            devs = jax.local_devices()
        if self.device_id >= len(devs):
            raise MXNetError(
                "context %s out of range: only %d %s device(s) visible"
                % (self, len(devs), self.device_type)
            )
        return devs[self.device_id]

    def empty_cache(self):
        """Best-effort analog of the reference's storage-pool release
        (ref: src/storage — Storage::Get()->ReleaseAll via MXStorageEmptyCache)."""
        import gc

        gc.collect()


def cpu(device_id=0):
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def gpu(device_id=0):
    """Alias for the accelerator device; kept for reference API compat."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    return Context("tpu", device_id)


def current_context() -> Context:
    """Innermost ``with ctx:`` scope, else default.

    Default is the accelerator when one is visible, else cpu — unlike the
    reference (which defaults to cpu) this puts users on TPU out of the box;
    ``with mx.cpu():`` opts out.
    """
    if _ctx_stack.stack:
        return _ctx_stack.stack[-1]
    return default_context()


_default_ctx = None


def default_context() -> Context:
    global _default_ctx
    if _default_ctx is None:
        import jax

        if jax.default_backend() == "cpu":
            _default_ctx = cpu(0)
        else:
            _default_ctx = tpu(0)
    return _default_ctx


def num_gpus() -> int:
    """Number of accelerator devices (reference: mx.context.num_gpus)."""
    return num_tpus()


def num_tpus() -> int:
    import jax

    if jax.default_backend() == "cpu":
        return 0
    return len(jax.devices())
