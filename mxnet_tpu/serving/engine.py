"""Decode engine — the AOT-warmed device half of the serving stack.

One fixed-shape donated jit program is the whole per-token hot path:
embed the batch's last tokens, project q/k/v per layer, append K/V into
the paged pool (scatter through the page table), run ragged paged
attention (ops/attention.py), sample greedily, return the next tokens —
``(k_pages, v_pages, context_lens, tokens)`` are donated through the
chain so the pool is appended in place at the XLA level.

Batch recomposition never recompiles: the program is always
``MXT_SERVING_SLOTS`` wide, inactive slots are masked (their KV writes
land on the cache's scratch page, their sampled token is held), and
joining/retiring a request is a handful of device ``.at[]`` edits on the
slot state arrays — all async dispatch, no host reads.

Host reads are the engine's whole game: the decode loop performs ZERO
per-step syncs. Sampled token ids ride the PR-4 in-flight window
(``engine.InflightWindow``) as staged per-step values — every K steps
ONE deferred transfer delivers a (K, slots) block of tokens to the
scheduler (``nd.PendingValue`` underneath), so host_syncs/step <= 1/K
exactly like the training stream, and ``tools/check_host_syncs.py``
lint-enforces it stays that way.

Admission is ONE fused shape-bucketed program per prefill bucket:
the prompt pass (padded to the bucket, ragged valid_length masks the
tail), the page-pool scatter, and the slot-state commit all land in a
single dispatch — on CPU each eager slot edit costs a real
millisecond, so admission used to dominate request rate. The first
sampled token returns to the scheduler as a PendingValue it
materializes at the next retirement boundary (one amortized read per
REQUEST, not per step). The active mask lives host-side and ships
with each dispatch, so activate/deactivate/release are flag flips.

``aot_warmup()`` lowers-and-compiles the decode step and every
bucket's fused admission program from live shapes; the engine
registers itself with ``tuning.register_step``, so a fresh replica's
``tuning.warmup()`` (plus the persistent compile cache) pays zero
request-path JIT — the PR-6 contract extended to serving.

``serving/speculative.py`` subclasses this engine to commit up to
``draft_k`` tokens per round (draft proposes, target verifies in one
wide launch) — :func:`one_token_pass` below is the shared per-token
core that makes the verify pass bit-identical to sequential decode.
"""
from __future__ import annotations

import functools

import numpy as np

from .. import engine as _engine
from ..base import MXNetError
from . import metrics as _m
from .kv_cache import PagedKVCache

__all__ = ["DecodeEngine", "one_token_pass"]


def one_token_pass(model, cache, params, kv, ctx, tokens, page_tables,
                   active, table_width, slots):
    """ONE decoder token step as a pure traced function: embed each
    slot's current token, append its K/V into the paged pool (inactive
    slots write the scratch page), attend the prefix through the page
    table, and greedy-sample the next token.

    This is the shared core of the plain decode step AND the
    speculative verify/draft programs (serving/speculative.py): the
    verify pass is literally this function unrolled k times, so a
    committed speculative token is computed by the bit-identical op
    sequence a sequential decode would have used — greedy
    token-exactness by construction, not by tolerance.

    Returns ``(kv_state, new_context_lens, next_tokens)``.
    """
    import jax.numpy as jnp

    from ..ops import attention as A

    S = cache.page_size
    scratch = cache.scratch_page
    actb = active.astype(bool)
    pos = ctx  # each slot's next KV index (== its current length)
    rows = jnp.arange(slots)
    # inactive slots write their (ignored) K/V to the scratch page
    page_idx = jnp.where(
        actb,
        page_tables[rows, jnp.clip(pos // S, 0, table_width - 1)],
        scratch)
    slot_idx = pos % S
    newlens = ctx + active

    h = model.embed(params, tokens,
                    jnp.clip(pos, 0, model.max_len - 1))
    for l in range(model.num_layers):
        q, kn, vn = model.layer_qkv(params, l, h)  # (B, H, D) each
        kv = cache.write_token(kv, l, page_idx, slot_idx, kn, vn)
        kl, vl, ks, vs = cache.attend_views(kv, l)
        attn = A.ragged_paged_attention(
            q, kl, vl, page_tables, newlens,
            sm_scale=model.sm_scale, k_scales=ks, v_scales=vs)
        h = model.layer_finish(params, l, h, attn)
    logits = model.logits(params, h)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    nxt = jnp.where(actb, nxt, tokens)  # inactive slots hold
    return kv, newlens, nxt


class DecodeEngine:
    """Fixed-slot decode executor over a :class:`PagedKVCache`."""

    # extra tokens reserved past prompt+max_new per sequence: the
    # speculative subclass sets this to its draft width (a verify pass
    # may write up to k-1 positions past the committed budget)
    _reserve_slack = 0
    # tokens a decode step may commit per slot (speculative: draft_k)
    tokens_per_step = 1

    def __init__(self, model, params=None, slots=None, cache=None,
                 prefill_buckets=(64, 256), max_context=None, seed=0):
        import jax
        import jax.numpy as jnp

        from .. import config, tuning

        self.model = model
        self.params = params if params is not None \
            else model.init_params(seed)
        self.slots = int(slots or config.get("MXT_SERVING_SLOTS"))
        if self.slots < 1:
            raise MXNetError("a decode engine needs at least one slot")
        self.cache = cache or PagedKVCache(
            model.num_layers, model.num_heads, model.head_dim)
        S = self.cache.page_size
        self.max_context = int(min(max_context or model.max_len,
                                   model.max_len))
        self.table_width = -(-(self.max_context
                               + self._reserve_slack) // S)

        B = self.slots
        scratch = self.cache.scratch_page
        self._tokens = jnp.zeros((B,), jnp.int32)
        self._ctx = jnp.zeros((B,), jnp.int32)
        self._pt = jnp.full((B, self.table_width), scratch, jnp.int32)
        # the active mask lives on HOST and ships with each dispatch
        # (one tiny h2d per step): activate/deactivate/release are then
        # pure flag flips instead of eager device edits — recomposition
        # costs nothing between launches
        self._host_active = np.zeros(B, bool)
        self._host_len = np.zeros(B, np.int64)
        self._seq_of_slot = {}

        # the K-deep deferred-read dispatch window (shared machinery
        # with the training StepStream); per-step sampled tokens stage
        # into it and retire as one stacked read per K steps
        self.on_tokens = None  # scheduler callback: (step_no, row, meta)
        self._inflight_meta = []  # per-push metadata, delivered in order
        self.window = _engine.InflightWindow(
            name="serving_decode", on_values=self._deliver)

        # tokens (arg 3) is NOT donated: each step's sampled-token array
        # is also staged in the in-flight window for the stacked
        # deferred read, and donating it on the next step would delete
        # a buffer the window still holds. arg 1 is the cache's whole
        # functional state tuple (pools + quantization scale planes).
        self._jit_step = jax.jit(self._step_impl,
                                 donate_argnums=(1, 2))
        self._buckets = sorted({self._round_bucket(b)
                                for b in prefill_buckets})
        self._admit_fns = {}
        tuning.register_step(self)
        # diagnostics HBM ledger: the replica's weights (the KV pool
        # registers itself in PagedKVCache). Host arithmetic on shape
        # metadata only — never a device read.
        from .. import diagnostics

        diagnostics.hbm_set(
            "params", "decode_engine",
            sum(l.nbytes for l in jax.tree_util.tree_leaves(self.params)
                if hasattr(l, "nbytes")))

    # -- shape bucketing --------------------------------------------------
    def _round_bucket(self, n):
        S = self.cache.page_size
        n = max(int(n), 1)
        return -(-(-(-n // 64) * 64) // S) * S

    def _bucket_for(self, n):
        """Smallest known prefill bucket covering ``n`` prompt tokens
        (a new bucket is minted — and becomes warmable — when traffic
        outgrows the configured ones)."""
        for b in self._buckets:
            if b >= n:
                return b
        b = self._round_bucket(n)
        self._buckets = sorted(set(self._buckets) | {b})
        return b

    # -- the decode hot path ----------------------------------------------
    def _step_impl(self, params, kv, ctx, tokens, page_tables, active):
        kv, newlens, nxt = one_token_pass(
            self.model, self.cache, params, kv, ctx, tokens,
            page_tables, active, self.table_width, self.slots)
        return kv, newlens, nxt

    def _ensure_pages(self, slots):
        """Grow page tables for slots whose next token crosses into an
        unallocated page (reservation-backed — cannot fail)."""
        import jax.numpy as jnp

        for s in slots:
            seq = self._seq_of_slot[s]
            if self.cache.alloc_for(seq, int(self._host_len[s]) + 1):
                row = self.cache.page_table_row(seq, self.table_width)
                self._pt = self._pt.at[s].set(jnp.asarray(row))

    def _active_arr(self):
        """This dispatch's active mask, built fresh from the host flags
        (host→device ship, never a read)."""
        import jax.numpy as jnp

        return jnp.asarray(self._host_active.astype(np.int32))

    def decode_step(self, meta=None):
        """Dispatch ONE decode step for every active slot; returns the
        window step number (None when no slot is active). ``meta`` is
        handed back untouched with this step's retired token row —
        the scheduler's slot→request attribution, kept out of the
        device program entirely."""
        act = [s for s in range(self.slots) if self._host_active[s]]
        if not act:
            return None
        self._ensure_pages(act)
        self._inflight_meta.append(meta)
        try:
            kv, ctx, tok = self._jit_step(
                self.params, self.cache.state(),
                self._ctx, self._tokens, self._pt, self._active_arr())
        except Exception as e:  # noqa: BLE001 — OOM gets the HBM ledger
            from .. import diagnostics

            self._inflight_meta.pop()
            diagnostics.reraise_if_oom(e, "serving_decode")
            raise
        self.cache.swap(kv)
        self._ctx, self._tokens = ctx, tok
        for s in act:
            self._host_len[s] += 1
        _m.tokens_total().inc(len(act))
        _m.decode_batch_occupancy().observe(len(act))
        return self.window.push(tok, value=tok)

    def _deliver(self, step_no, row):
        """InflightWindow retirement: one host row of sampled tokens per
        step, oldest first — metadata pops in the same order pushes
        appended it."""
        meta = self._inflight_meta.pop(0) if self._inflight_meta else None
        cb = self.on_tokens
        if cb is not None:
            cb(step_no, row, meta)

    def decode_row(self, row, slot):
        """The tokens one retired step row carries for ``slot`` —
        exactly one for the plain engine. The speculative subclass
        returns the whole accepted prefix (variable length), which is
        why the scheduler asks the engine instead of indexing the row
        itself."""
        return [int(row[slot])]

    def can_admit(self, total_tokens):
        """Whether admission-side page reservations for a request of
        ``total_tokens`` (prompt + max_new) would succeed right now —
        the scheduler's gate. Covers the engine's reservation slack and
        (in the speculative subclass) the draft cache too."""
        return self.cache.can_reserve(total_tokens + self._reserve_slack)

    def flush(self):
        """Drain the in-flight window (every dispatched step's tokens
        delivered). The scheduler's barrier; nd.waitall() also reaches
        it through engine.wait_all."""
        self.window.flush()

    # -- prefill ----------------------------------------------------------
    def _prefill_impl(self, params, tokens, valid, *, bucket):
        import jax.numpy as jnp

        model = self.model
        S = self.cache.page_size
        nbp = bucket // S
        ks, vs, logits = model.prefill(params, tokens, valid)
        # (L, 1, H, T, D) -> page-shaped (L, nbp, S, H, D)
        kr = jnp.transpose(ks[:, 0], (0, 2, 1, 3)).reshape(
            model.num_layers, nbp, S, model.num_heads, model.head_dim)
        vr = jnp.transpose(vs[:, 0], (0, 2, 1, 3)).reshape(
            model.num_layers, nbp, S, model.num_heads, model.head_dim)
        tok0 = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (1,)
        # pages leave this program at compute dtype; the page-write
        # program casts (or quantizes) into the pool's storage dtype
        return kr, vr, tok0

    def _admit_impl(self, params, kv, pt, tokens, ctx, padded, valid,
                    ids, row, slot, t, *, bucket):
        """The whole device side of one admission as ONE program:
        bucketed prompt prefill, page-pool scatter, and the slot-state
        commit (page-table row, first sampled token, context length).
        Admission used to cost ~5 eager dispatches; on CPU each eager
        scatter is a real millisecond, so fusing them is a measurable
        request-rate win."""
        kpag, vpag, tok0 = self._prefill_impl(params, padded, valid,
                                              bucket=bucket)
        kv = self.cache.write_pages(kv, kpag, vpag, ids)
        return (kv, pt.at[slot].set(row), tokens.at[slot].set(tok0[0]),
                ctx.at[slot].set(t), tok0)

    def _admit_fn(self, bucket):
        import jax

        fn = self._admit_fns.get(bucket)
        if fn is None:
            fn = self._admit_fns[bucket] = jax.jit(
                functools.partial(self._admit_impl, bucket=bucket),
                donate_argnums=(1, 2, 4))
        return fn

    def _admit_prep(self, slot, seq_id, prompt_tokens, max_new_tokens):
        """Host half of admission: validation, worst-case reservation,
        upfront allocation, and the padded/ids/row arrays the fused
        admit program consumes."""
        if self._host_active[slot] or slot in self._seq_of_slot:
            raise MXNetError("slot %d is occupied" % slot)
        prompt = np.array(list(prompt_tokens), np.int32)
        T = int(prompt.shape[0])
        total = T + int(max_new_tokens)
        if T < 1:
            raise MXNetError("empty prompt")
        if total > self.max_context:
            raise MXNetError(
                "prompt+max_new = %d exceeds the engine's max context %d"
                % (total, self.max_context))
        # slack covers speculative-verify overshoot past the budget
        if not self.cache.reserve(seq_id, total + self._reserve_slack):
            raise MXNetError("KV pool too busy for sequence %r (check "
                             "engine.can_admit before admitting)"
                             % (seq_id,))
        self._post_reserve(seq_id, total)
        bucket = self._bucket_for(T)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :T] = prompt
        self.cache.alloc_for(seq_id, T)
        pages = self.cache.pages_of(seq_id)
        nbp = bucket // self.cache.page_size
        ids = np.full((nbp,), self.cache.scratch_page, np.int32)
        n = min(len(pages), nbp)  # upfront-allocated tails stay put
        ids[:n] = pages[:n]  # bucket tail pages scatter to scratch
        row = self.cache.page_table_row(seq_id, self.table_width)
        return {"T": T, "bucket": bucket, "padded": padded, "ids": ids,
                "row": row, "prompt": prompt}

    def admit(self, slot, seq_id, prompt_tokens, max_new_tokens):
        """Prefill a request into a free slot: reserve its worst-case
        pages, then ONE fused dispatch runs the bucketed prompt pass,
        scatters the prompt K/V into the pool, and seeds the slot with
        the first sampled token.

        Returns a PendingValue of that first token — deferred like
        everything else; the scheduler materializes it at a retirement
        boundary (the prefill has certainly finished by then)."""
        import jax.numpy as jnp

        from ..ndarray.pending import PendingValue

        p = self._admit_prep(slot, seq_id, prompt_tokens, max_new_tokens)
        try:
            kv, self._pt, self._tokens, self._ctx, tok0 = \
                self._admit_fn(p["bucket"])(
                    self.params, self.cache.state(), self._pt,
                    self._tokens, self._ctx, jnp.asarray(p["padded"]),
                    jnp.asarray(np.array([p["T"]], np.int32)),
                    jnp.asarray(p["ids"]), jnp.asarray(p["row"]),
                    np.int32(slot), np.int32(p["T"]))
        except Exception as e:  # noqa: BLE001 — OOM gets the HBM ledger
            from .. import diagnostics

            self.cache.free(seq_id)  # release the admission reservation
            diagnostics.reraise_if_oom(e, "serving_prefill")
            raise
        self.cache.swap(kv)
        self._seq_of_slot[slot] = seq_id
        self._host_active[slot] = True
        self._host_len[slot] = p["T"]
        _m.tokens_total().inc()  # the prefill-sampled first token
        return PendingValue(tok0)

    def _post_reserve(self, seq_id, total):
        """Subclass hook: runs right after the admission reservation,
        before the prompt's pages allocate (the speculative engine
        materializes its full worst-case allocation here so the page
        table row is written complete, once)."""

    # -- recomposition ----------------------------------------------------
    def deactivate(self, slot):
        """Stop decoding a slot without releasing its pages (static
        batching's idle state; also the first half of release). A pure
        host flag flip — the mask ships with the next dispatch."""
        self._host_active[slot] = False

    def activate(self, slot):
        """Resume decoding a deactivated slot (its pages, context and
        current token were preserved). The speculative scheduler parks
        slots here while their budget is possibly complete in flight —
        a parked slot must NOT advance device-side, or tokens would be
        committed that the host never attributes."""
        if slot in self._seq_of_slot:
            self._host_active[slot] = True

    def release(self, slot):
        """Retire a slot: deactivate and free the sequence's pages and
        reservation. The stale page-table row stays — an inactive
        slot's reads are fully masked and its writes go to scratch, and
        the next admission overwrites the row — so recomposition costs
        zero device edits. In-flight steps still referencing the freed
        pages read the old pool *values* (dataflow), so this is safe
        mid-window."""
        self.deactivate(slot)
        seq = self._seq_of_slot.pop(slot, None)
        if seq is not None:
            self.cache.free(seq)
        self._host_len[slot] = 0

    def defrag(self):
        """Compact the KV pool and re-emit live slots' page-table rows
        against the moved page ids."""
        import jax.numpy as jnp

        moved = self.cache.defrag()
        if moved:
            for s, seq in self._seq_of_slot.items():
                self._pt = self._pt.at[s].set(jnp.asarray(
                    self.cache.page_table_row(seq, self.table_width)))
        return moved

    # -- AOT warm-start ---------------------------------------------------
    def aot_warmup(self):
        """Lower-and-compile every request-path program from live
        shapes: the decode step, each prefill bucket, and the page-write
        scatters. With MXT_COMPILE_CACHE_DIR set, a later replica
        replays all of it from disk — zero JIT on the request path."""
        import jax
        import jax.numpy as jnp

        def sds(a):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        i32 = jnp.int32
        pstruct = jax.tree_util.tree_map(sds, self.params)
        kv_sds = tuple(sds(a) for a in self.cache.state())
        n = 0
        self._jit_step.lower(
            pstruct, kv_sds, sds(self._ctx), sds(self._tokens),
            sds(self._pt),
            jax.ShapeDtypeStruct((self.slots,), i32)).compile()
        n += 1
        S = self.cache.page_size
        for bucket in list(self._buckets):
            self._admit_fn(bucket).lower(
                pstruct, kv_sds, sds(self._pt), sds(self._tokens),
                sds(self._ctx),
                jax.ShapeDtypeStruct((1, bucket), i32),
                jax.ShapeDtypeStruct((1,), i32),
                jax.ShapeDtypeStruct((bucket // S,), i32),
                jax.ShapeDtypeStruct((self.table_width,), i32),
                jax.ShapeDtypeStruct((), i32),
                jax.ShapeDtypeStruct((), i32)).compile()
            n += 1
        return n
