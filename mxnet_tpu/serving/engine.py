"""Decode engine — the AOT-warmed device half of the serving stack.

One fixed-shape donated jit program is the whole per-token hot path:
embed the batch's last tokens, project q/k/v per layer, append K/V into
the paged pool (scatter through the page table), run ragged paged
attention (ops/attention.py), sample greedily, return the next tokens —
``(k_pages, v_pages, context_lens, tokens)`` are donated through the
chain so the pool is appended in place at the XLA level.

Batch recomposition never recompiles: the program is always
``MXT_SERVING_SLOTS`` wide, inactive slots are masked (their KV writes
land on the cache's scratch page, their sampled token is held), and
joining/retiring a request is a handful of device ``.at[]`` edits on the
slot state arrays — all async dispatch, no host reads.

Host reads are the engine's whole game: the decode loop performs ZERO
per-step syncs. Sampled token ids ride the PR-4 in-flight window
(``engine.InflightWindow``) as staged per-step values — every K steps
ONE deferred transfer delivers a (K, slots) block of tokens to the
scheduler (``nd.PendingValue`` underneath), so host_syncs/step <= 1/K
exactly like the training stream, and ``tools/check_host_syncs.py``
lint-enforces it stays that way.

Prefill runs per request through shape-bucketed jit programs (prompt
padded to the bucket, ragged valid_length masks the tail), writes the
prompt's K/V pages with a donated scatter, and seeds the slot with the
first sampled token — returned to the scheduler as a PendingValue it
materializes at the next retirement boundary (one amortized read per
REQUEST, not per step).

``aot_warmup()`` lowers-and-compiles the decode step, every prefill
bucket, and the page-write programs from live shapes; the engine
registers itself with ``tuning.register_step``, so a fresh replica's
``tuning.warmup()`` (plus the persistent compile cache) pays zero
request-path JIT — the PR-6 contract extended to serving.
"""
from __future__ import annotations

import functools

import numpy as np

from .. import engine as _engine
from ..base import MXNetError
from . import metrics as _m
from .kv_cache import PagedKVCache

__all__ = ["DecodeEngine"]


class DecodeEngine:
    """Fixed-slot decode executor over a :class:`PagedKVCache`."""

    def __init__(self, model, params=None, slots=None, cache=None,
                 prefill_buckets=(64, 256), max_context=None, seed=0):
        import jax
        import jax.numpy as jnp

        from .. import config, tuning

        self.model = model
        self.params = params if params is not None \
            else model.init_params(seed)
        self.slots = int(slots or config.get("MXT_SERVING_SLOTS"))
        if self.slots < 1:
            raise MXNetError("a decode engine needs at least one slot")
        self.cache = cache or PagedKVCache(
            model.num_layers, model.num_heads, model.head_dim)
        S = self.cache.page_size
        self.max_context = int(min(max_context or model.max_len,
                                   model.max_len))
        self.table_width = -(-self.max_context // S)

        B = self.slots
        scratch = self.cache.scratch_page
        self._tokens = jnp.zeros((B,), jnp.int32)
        self._ctx = jnp.zeros((B,), jnp.int32)
        self._active = jnp.zeros((B,), jnp.int32)
        self._pt = jnp.full((B, self.table_width), scratch, jnp.int32)
        self._host_active = np.zeros(B, bool)
        self._host_len = np.zeros(B, np.int64)
        self._seq_of_slot = {}

        # the K-deep deferred-read dispatch window (shared machinery
        # with the training StepStream); per-step sampled tokens stage
        # into it and retire as one stacked read per K steps
        self.on_tokens = None  # scheduler callback: (step_no, row, meta)
        self._inflight_meta = []  # per-push metadata, delivered in order
        self.window = _engine.InflightWindow(
            name="serving_decode", on_values=self._deliver)

        # tokens (arg 4) is NOT donated: each step's sampled-token array
        # is also staged in the in-flight window for the stacked
        # deferred read, and donating it on the next step would delete
        # a buffer the window still holds
        self._jit_step = jax.jit(self._step_impl,
                                 donate_argnums=(1, 2, 3))
        self._buckets = sorted({self._round_bucket(b)
                                for b in prefill_buckets})
        self._prefill_fns = {}
        self._write_fns = {}
        tuning.register_step(self)
        # diagnostics HBM ledger: the replica's weights (the KV pool
        # registers itself in PagedKVCache). Host arithmetic on shape
        # metadata only — never a device read.
        from .. import diagnostics

        diagnostics.hbm_set(
            "params", "decode_engine",
            sum(l.nbytes for l in jax.tree_util.tree_leaves(self.params)
                if hasattr(l, "nbytes")))

    # -- shape bucketing --------------------------------------------------
    def _round_bucket(self, n):
        S = self.cache.page_size
        n = max(int(n), 1)
        return -(-(-(-n // 64) * 64) // S) * S

    def _bucket_for(self, n):
        """Smallest known prefill bucket covering ``n`` prompt tokens
        (a new bucket is minted — and becomes warmable — when traffic
        outgrows the configured ones)."""
        for b in self._buckets:
            if b >= n:
                return b
        b = self._round_bucket(n)
        self._buckets = sorted(set(self._buckets) | {b})
        return b

    # -- the decode hot path ----------------------------------------------
    def _step_impl(self, params, k_pages, v_pages, ctx, tokens,
                   page_tables, active):
        import jax.numpy as jnp

        from ..ops import attention as A

        model = self.model
        S = self.cache.page_size
        scratch = self.cache.scratch_page
        actb = active.astype(bool)
        pos = ctx  # each slot's next KV index (== its current length)
        rows = jnp.arange(self.slots)
        # inactive slots write their (ignored) K/V to the scratch page
        page_idx = jnp.where(
            actb,
            page_tables[rows, jnp.clip(pos // S, 0, self.table_width - 1)],
            scratch)
        slot_idx = pos % S
        newlens = ctx + active

        h = model.embed(params, tokens,
                        jnp.clip(pos, 0, model.max_len - 1))
        for l in range(model.num_layers):
            q, kn, vn = model.layer_qkv(params, l, h)  # (B, H, D) each
            k_pages = k_pages.at[l, page_idx, slot_idx].set(
                kn.astype(k_pages.dtype))
            v_pages = v_pages.at[l, page_idx, slot_idx].set(
                vn.astype(v_pages.dtype))
            attn = A.ragged_paged_attention(
                q, k_pages[l], v_pages[l], page_tables, newlens,
                sm_scale=model.sm_scale)
            h = model.layer_finish(params, l, h, attn)
        logits = model.logits(params, h)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(actb, nxt, tokens)  # inactive slots hold
        return k_pages, v_pages, newlens, nxt

    def _ensure_pages(self, slots):
        """Grow page tables for slots whose next token crosses into an
        unallocated page (reservation-backed — cannot fail)."""
        import jax.numpy as jnp

        for s in slots:
            seq = self._seq_of_slot[s]
            if self.cache.alloc_for(seq, int(self._host_len[s]) + 1):
                row = self.cache.page_table_row(seq, self.table_width)
                self._pt = self._pt.at[s].set(jnp.asarray(row))

    def decode_step(self, meta=None):
        """Dispatch ONE decode step for every active slot; returns the
        window step number (None when no slot is active). ``meta`` is
        handed back untouched with this step's retired token row —
        the scheduler's slot→request attribution, kept out of the
        device program entirely."""
        act = [s for s in range(self.slots) if self._host_active[s]]
        if not act:
            return None
        self._ensure_pages(act)
        self._inflight_meta.append(meta)
        try:
            kp, vp, ctx, tok = self._jit_step(
                self.params, self.cache.k_pages, self.cache.v_pages,
                self._ctx, self._tokens, self._pt, self._active)
        except Exception as e:  # noqa: BLE001 — OOM gets the HBM ledger
            from .. import diagnostics

            self._inflight_meta.pop()
            diagnostics.reraise_if_oom(e, "serving_decode")
            raise
        self.cache.swap(kp, vp)
        self._ctx, self._tokens = ctx, tok
        for s in act:
            self._host_len[s] += 1
        _m.tokens_total().inc(len(act))
        _m.decode_batch_occupancy().observe(len(act))
        return self.window.push(tok, value=tok)

    def _deliver(self, step_no, row):
        """InflightWindow retirement: one host row of sampled tokens per
        step, oldest first — metadata pops in the same order pushes
        appended it."""
        meta = self._inflight_meta.pop(0) if self._inflight_meta else None
        cb = self.on_tokens
        if cb is not None:
            cb(step_no, row, meta)

    def flush(self):
        """Drain the in-flight window (every dispatched step's tokens
        delivered). The scheduler's barrier; nd.waitall() also reaches
        it through engine.wait_all."""
        self.window.flush()

    # -- prefill ----------------------------------------------------------
    def _prefill_impl(self, params, tokens, valid, *, bucket):
        import jax.numpy as jnp

        model = self.model
        S = self.cache.page_size
        nbp = bucket // S
        ks, vs, logits = model.prefill(params, tokens, valid)
        # (L, 1, H, T, D) -> page-shaped (L, nbp, S, H, D)
        kr = jnp.transpose(ks[:, 0], (0, 2, 1, 3)).reshape(
            model.num_layers, nbp, S, model.num_heads, model.head_dim)
        vr = jnp.transpose(vs[:, 0], (0, 2, 1, 3)).reshape(
            model.num_layers, nbp, S, model.num_heads, model.head_dim)
        tok0 = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (1,)
        return (kr.astype(self.cache.dtype), vr.astype(self.cache.dtype),
                tok0)

    def _prefill_fn(self, bucket):
        import jax

        fn = self._prefill_fns.get(bucket)
        if fn is None:
            fn = self._prefill_fns[bucket] = jax.jit(
                functools.partial(self._prefill_impl, bucket=bucket))
        return fn

    def _write_fn(self, nbp):
        import jax

        fn = self._write_fns.get(nbp)
        if fn is None:
            def write(kp, vp, kn, vn, ids):
                return kp.at[:, ids].set(kn), vp.at[:, ids].set(vn)

            fn = self._write_fns[nbp] = jax.jit(write,
                                                donate_argnums=(0, 1))
        return fn

    def admit(self, slot, seq_id, prompt_tokens, max_new_tokens):
        """Prefill a request into a free slot: reserve its worst-case
        pages, run the bucketed prompt pass, scatter the prompt K/V into
        the pool, and seed the slot with the first sampled token.

        Returns a PendingValue of that first token — deferred like
        everything else; the scheduler materializes it at a retirement
        boundary (the prefill has certainly finished by then)."""
        import jax.numpy as jnp

        from ..ndarray.pending import PendingValue

        if self._host_active[slot] or slot in self._seq_of_slot:
            raise MXNetError("slot %d is occupied" % slot)
        prompt = np.array(list(prompt_tokens), np.int32)
        T = int(prompt.shape[0])
        total = T + int(max_new_tokens)
        if T < 1:
            raise MXNetError("empty prompt")
        if total > self.max_context:
            raise MXNetError(
                "prompt+max_new = %d exceeds the engine's max context %d"
                % (total, self.max_context))
        if not self.cache.reserve(seq_id, total):
            raise MXNetError("KV pool too busy for sequence %r (check "
                             "cache.can_reserve before admitting)"
                             % (seq_id,))
        bucket = self._bucket_for(T)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :T] = prompt
        try:
            kpag, vpag, tok0 = self._prefill_fn(bucket)(
                self.params, jnp.asarray(padded),
                jnp.asarray(np.array([T], np.int32)))
        except Exception as e:  # noqa: BLE001 — OOM gets the HBM ledger
            from .. import diagnostics

            self.cache.free(seq_id)  # release the admission reservation
            diagnostics.reraise_if_oom(e, "serving_prefill")
            raise
        self.cache.alloc_for(seq_id, T)
        pages = self.cache.pages_of(seq_id)
        nbp = bucket // self.cache.page_size
        ids = np.full((nbp,), self.cache.scratch_page, np.int32)
        ids[:len(pages)] = pages  # bucket tail pages scatter to scratch
        kp, vp = self._write_fn(nbp)(
            self.cache.k_pages, self.cache.v_pages, kpag, vpag,
            jnp.asarray(ids))
        self.cache.swap(kp, vp)

        self._seq_of_slot[slot] = seq_id
        self._host_active[slot] = True
        self._host_len[slot] = T
        self._pt = self._pt.at[slot].set(
            jnp.asarray(self.cache.page_table_row(seq_id,
                                                  self.table_width)))
        self._tokens = self._tokens.at[slot].set(tok0[0])
        self._ctx = self._ctx.at[slot].set(T)
        self._active = self._active.at[slot].set(1)
        _m.tokens_total().inc()  # the prefill-sampled first token
        return PendingValue(tok0)

    # -- recomposition ----------------------------------------------------
    def deactivate(self, slot):
        """Stop decoding a slot without releasing its pages (static
        batching's idle state; also the first half of release)."""
        if self._host_active[slot]:
            self._host_active[slot] = False
            self._active = self._active.at[slot].set(0)

    def release(self, slot):
        """Retire a slot: deactivate, free the sequence's pages and
        reservation, and point its page-table row back at scratch.
        In-flight steps still referencing the old pages read the old
        pool *values* (dataflow), so this is safe mid-window."""
        import jax.numpy as jnp

        self.deactivate(slot)
        seq = self._seq_of_slot.pop(slot, None)
        if seq is not None:
            self.cache.free(seq)
        self._host_len[slot] = 0
        self._pt = self._pt.at[slot].set(
            jnp.full((self.table_width,), self.cache.scratch_page,
                     jnp.int32))

    def defrag(self):
        """Compact the KV pool and re-emit live slots' page-table rows
        against the moved page ids."""
        import jax.numpy as jnp

        moved = self.cache.defrag()
        if moved:
            for s, seq in self._seq_of_slot.items():
                self._pt = self._pt.at[s].set(jnp.asarray(
                    self.cache.page_table_row(seq, self.table_width)))
        return moved

    # -- AOT warm-start ---------------------------------------------------
    def aot_warmup(self):
        """Lower-and-compile every request-path program from live
        shapes: the decode step, each prefill bucket, and the page-write
        scatters. With MXT_COMPILE_CACHE_DIR set, a later replica
        replays all of it from disk — zero JIT on the request path."""
        import jax
        import jax.numpy as jnp

        def sds(a):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        pstruct = jax.tree_util.tree_map(sds, self.params)
        n = 0
        self._jit_step.lower(
            pstruct, sds(self.cache.k_pages), sds(self.cache.v_pages),
            sds(self._ctx), sds(self._tokens), sds(self._pt),
            sds(self._active)).compile()
        n += 1
        L, H, D = (self.model.num_layers, self.model.num_heads,
                   self.model.head_dim)
        S = self.cache.page_size
        for bucket in list(self._buckets):
            nbp = bucket // S
            self._prefill_fn(bucket).lower(
                pstruct,
                jax.ShapeDtypeStruct((1, bucket), jnp.int32),
                jax.ShapeDtypeStruct((1,), jnp.int32)).compile()
            pool = jax.ShapeDtypeStruct(
                (L, nbp, S, H, D), self.cache.dtype)
            self._write_fn(nbp).lower(
                sds(self.cache.k_pages), sds(self.cache.v_pages),
                pool, pool,
                jax.ShapeDtypeStruct((nbp,), jnp.int32)).compile()
            n += 2
        return n
