"""Decode engine — the AOT-warmed device half of the serving stack.

One fixed-shape donated jit program is the whole per-token hot path:
embed the batch's last tokens, project q/k/v per layer, append K/V into
the paged pool (scatter through the page table), run ragged paged
attention (ops/attention.py), sample greedily, return the next tokens —
``(k_pages, v_pages, context_lens, tokens)`` are donated through the
chain so the pool is appended in place at the XLA level.

Batch recomposition never recompiles: the program is always
``MXT_SERVING_SLOTS`` wide, inactive slots are masked (their KV writes
land on the cache's scratch page, their sampled token is held), and
joining/retiring a request is a handful of device ``.at[]`` edits on the
slot state arrays — all async dispatch, no host reads.

Host reads are the engine's whole game: the decode loop performs ZERO
per-step syncs. Sampled token ids ride the PR-4 in-flight window
(``engine.InflightWindow``) as staged per-step values — every K steps
ONE deferred transfer delivers a (K, slots) block of tokens to the
scheduler (``nd.PendingValue`` underneath), so host_syncs/step <= 1/K
exactly like the training stream, and ``tools/check_host_syncs.py``
lint-enforces it stays that way.

Admission is ONE fused shape-bucketed program per prefill bucket:
the prompt pass (padded to the bucket, ragged valid_length masks the
tail), the page-pool scatter, and the slot-state commit all land in a
single dispatch — on CPU each eager slot edit costs a real
millisecond, so admission used to dominate request rate. The first
sampled token returns to the scheduler as a PendingValue it
materializes at the next retirement boundary (one amortized read per
REQUEST, not per step). The active mask lives host-side and ships
with each dispatch, so activate/deactivate/release are flag flips.

``aot_warmup()`` lowers-and-compiles the decode step and every
bucket's fused admission program from live shapes; the engine
registers itself with ``tuning.register_step``, so a fresh replica's
``tuning.warmup()`` (plus the persistent compile cache) pays zero
request-path JIT — the PR-6 contract extended to serving.

``serving/speculative.py`` subclasses this engine to commit up to
``draft_k`` tokens per round (draft proposes, target verifies in one
wide launch) — :func:`one_token_pass` below is the shared per-token
core that makes the verify pass bit-identical to sequential decode.
"""
from __future__ import annotations

import functools

import numpy as np

from .. import engine as _engine
from ..base import MXNetError
from . import metrics as _m
from .kv_cache import PagedKVCache

__all__ = ["DecodeEngine", "one_token_pass"]


def one_token_pass(model, cache, params, kv, ctx, tokens, page_tables,
                   active, table_width, slots):
    """ONE decoder token step as a pure traced function: embed each
    slot's current token, append its K/V into the paged pool (inactive
    slots write the scratch page), attend the prefix through the page
    table, and greedy-sample the next token.

    This is the shared core of the plain decode step AND the
    speculative verify/draft programs (serving/speculative.py): the
    verify pass is literally this function unrolled k times, so a
    committed speculative token is computed by the bit-identical op
    sequence a sequential decode would have used — greedy
    token-exactness by construction, not by tolerance.

    Returns ``(kv_state, new_context_lens, next_tokens)``.
    """
    import jax.numpy as jnp

    from ..ops import attention as A

    S = cache.page_size
    scratch = cache.scratch_page
    actb = active.astype(bool)
    pos = ctx  # each slot's next KV index (== its current length)
    rows = jnp.arange(slots)
    # inactive slots write their (ignored) K/V to the scratch page
    page_idx = jnp.where(
        actb,
        page_tables[rows, jnp.clip(pos // S, 0, table_width - 1)],
        scratch)
    slot_idx = pos % S
    newlens = ctx + active

    h = model.embed(params, tokens,
                    jnp.clip(pos, 0, model.max_len - 1))
    for l in range(model.num_layers):
        q, kn, vn = model.layer_qkv(params, l, h)  # (B, H, D) each
        kv = cache.write_token(kv, l, page_idx, slot_idx, kn, vn)
        kl, vl, ks, vs = cache.attend_views(kv, l)
        attn = A.ragged_paged_attention(
            q, kl, vl, page_tables, newlens,
            sm_scale=model.sm_scale, k_scales=ks, v_scales=vs)
        h = model.layer_finish(params, l, h, attn)
    logits = model.logits(params, h)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    nxt = jnp.where(actb, nxt, tokens)  # inactive slots hold
    return kv, newlens, nxt


class DecodeEngine:
    """Fixed-slot decode executor over a :class:`PagedKVCache`."""

    # extra tokens reserved past prompt+max_new per sequence: the
    # speculative subclass sets this to its draft width (a verify pass
    # may write up to k-1 positions past the committed budget)
    _reserve_slack = 0
    # tokens a decode step may commit per slot (speculative: draft_k)
    tokens_per_step = 1

    def __init__(self, model, params=None, slots=None, cache=None,
                 prefill_buckets=(64, 256), max_context=None, seed=0,
                 prefix_cache=False):
        import jax
        import jax.numpy as jnp

        from .. import config, tuning

        self.model = model
        self.params = params if params is not None \
            else model.init_params(seed)
        self.slots = int(slots or config.get("MXT_SERVING_SLOTS"))
        if self.slots < 1:
            raise MXNetError("a decode engine needs at least one slot")
        self.cache = cache or PagedKVCache(
            model.num_layers, model.num_heads, model.head_dim)
        S = self.cache.page_size
        self.max_context = int(min(max_context or model.max_len,
                                   model.max_len))
        self.table_width = -(-(self.max_context
                               + self._reserve_slack) // S)

        B = self.slots
        scratch = self.cache.scratch_page
        self._tokens = jnp.zeros((B,), jnp.int32)
        self._ctx = jnp.zeros((B,), jnp.int32)
        self._pt = jnp.full((B, self.table_width), scratch, jnp.int32)
        # the active mask lives on HOST and ships with each dispatch
        # (one tiny h2d per step): activate/deactivate/release are then
        # pure flag flips instead of eager device edits — recomposition
        # costs nothing between launches
        self._host_active = np.zeros(B, bool)
        self._host_len = np.zeros(B, np.int64)
        self._seq_of_slot = {}

        # the K-deep deferred-read dispatch window (shared machinery
        # with the training StepStream); per-step sampled tokens stage
        # into it and retire as one stacked read per K steps
        self.on_tokens = None  # scheduler callback: (step_no, row, meta)
        self._inflight_meta = []  # per-push metadata, delivered in order
        self.window = _engine.InflightWindow(
            name="serving_decode", on_values=self._deliver)

        # tokens (arg 3) is NOT donated: each step's sampled-token array
        # is also staged in the in-flight window for the stacked
        # deferred read, and donating it on the next step would delete
        # a buffer the window still holds. arg 1 is the cache's whole
        # functional state tuple (pools + quantization scale planes).
        self._jit_step = jax.jit(self._step_impl,
                                 donate_argnums=(1, 2))
        self._buckets = sorted({self._round_bucket(b)
                                for b in prefill_buckets})
        self._admit_fns = {}
        # shared-prefix reuse (serving/prefix.py): opt-in because the
        # speculative subclass and draft caches don't compose with
        # page sharing (the verify overshoot writes into prompt pages)
        self.prefix = None
        if prefix_cache:
            from .prefix import PrefixIndex

            self.prefix = PrefixIndex(self.cache)
        self._prefix_admit_fns = {}
        self._adopt_fns = {}
        tuning.register_step(self)
        # diagnostics HBM ledger: the replica's weights (the KV pool
        # registers itself in PagedKVCache). Host arithmetic on shape
        # metadata only — never a device read.
        from .. import diagnostics

        diagnostics.hbm_set(
            "params", "decode_engine",
            sum(l.nbytes for l in jax.tree_util.tree_leaves(self.params)
                if hasattr(l, "nbytes")))

    # -- shape bucketing --------------------------------------------------
    def _round_bucket(self, n):
        S = self.cache.page_size
        n = max(int(n), 1)
        return -(-(-(-n // 64) * 64) // S) * S

    def _bucket_for(self, n):
        """Smallest known prefill bucket covering ``n`` prompt tokens
        (a new bucket is minted — and becomes warmable — when traffic
        outgrows the configured ones)."""
        for b in self._buckets:
            if b >= n:
                return b
        b = self._round_bucket(n)
        self._buckets = sorted(set(self._buckets) | {b})
        return b

    # -- the decode hot path ----------------------------------------------
    def _step_impl(self, params, kv, ctx, tokens, page_tables, active):
        kv, newlens, nxt = one_token_pass(
            self.model, self.cache, params, kv, ctx, tokens,
            page_tables, active, self.table_width, self.slots)
        return kv, newlens, nxt

    def _ensure_pages(self, slots):
        """Grow page tables for slots whose next token crosses into an
        unallocated page (reservation-backed — cannot fail)."""
        import jax.numpy as jnp

        for s in slots:
            seq = self._seq_of_slot[s]
            if self.cache.alloc_for(seq, int(self._host_len[s]) + 1):
                row = self.cache.page_table_row(seq, self.table_width)
                self._pt = self._pt.at[s].set(jnp.asarray(row))

    def _active_arr(self):
        """This dispatch's active mask, built fresh from the host flags
        (host→device ship, never a read)."""
        import jax.numpy as jnp

        return jnp.asarray(self._host_active.astype(np.int32))

    def decode_step(self, meta=None):
        """Dispatch ONE decode step for every active slot; returns the
        window step number (None when no slot is active). ``meta`` is
        handed back untouched with this step's retired token row —
        the scheduler's slot→request attribution, kept out of the
        device program entirely."""
        act = [s for s in range(self.slots) if self._host_active[s]]
        if not act:
            return None
        self._ensure_pages(act)
        self._inflight_meta.append(meta)
        try:
            kv, ctx, tok = self._jit_step(
                self.params, self.cache.state(),
                self._ctx, self._tokens, self._pt, self._active_arr())
        except Exception as e:  # noqa: BLE001 — OOM gets the HBM ledger
            from .. import diagnostics

            self._inflight_meta.pop()
            diagnostics.reraise_if_oom(e, "serving_decode")
            raise
        self.cache.swap(kv)
        self._ctx, self._tokens = ctx, tok
        for s in act:
            self._host_len[s] += 1
        _m.tokens_total().inc(len(act))
        _m.decode_batch_occupancy().observe(len(act))
        return self.window.push(tok, value=tok)

    def _deliver(self, step_no, row):
        """InflightWindow retirement: one host row of sampled tokens per
        step, oldest first — metadata pops in the same order pushes
        appended it."""
        meta = self._inflight_meta.pop(0) if self._inflight_meta else None
        cb = self.on_tokens
        if cb is not None:
            cb(step_no, row, meta)

    def decode_row(self, row, slot):
        """The tokens one retired step row carries for ``slot`` —
        exactly one for the plain engine. The speculative subclass
        returns the whole accepted prefix (variable length), which is
        why the scheduler asks the engine instead of indexing the row
        itself."""
        return [int(row[slot])]

    def can_admit(self, total_tokens, prompt=None):
        """Whether admission-side page reservations for a request of
        ``total_tokens`` (prompt + max_new) would succeed right now —
        the scheduler's gate. Covers the engine's reservation slack and
        (in the speculative subclass) the draft cache too. With a
        prefix index and the prompt in hand, a cached prefix discounts
        the page bill, and under pool pressure cold index entries are
        shed (LRU) before giving up — index-pinned pages are capacity,
        not a leak."""
        total = total_tokens + self._reserve_slack
        if self.prefix is None or prompt is None:
            return self.cache.can_reserve(total)
        pages, covered, chain = self.prefix.lookup(prompt)
        shared, cow, _ = self._share_plan(len(prompt), pages, covered)
        need = self.cache.pages_needed(total) - len(shared) + cow
        if self.cache.available() >= need:
            return True
        keep = chain[:len(shared)] if shared else ()
        return self.prefix.trim(need, keep=keep)

    def _share_plan(self, T, pages, covered):
        """(shared_pages, cow_debt, start) for a prefix-index hit on a
        ``T``-token prompt: a partial hit prefills from the first
        uncovered token; a FULL match (page-aligned prompt entirely
        cached) still recomputes the last token — its K/V write lands
        in the final shared page, which is the one copy-on-write."""
        if not covered:
            return [], 0, 0
        if covered >= T:
            return list(pages), 1, T - 1
        return list(pages), 0, covered

    def flush(self):
        """Drain the in-flight window (every dispatched step's tokens
        delivered). The scheduler's barrier; nd.waitall() also reaches
        it through engine.wait_all."""
        self.window.flush()

    # -- prefill ----------------------------------------------------------
    def _prefill_impl(self, params, tokens, valid, *, bucket):
        import jax.numpy as jnp

        model = self.model
        S = self.cache.page_size
        nbp = bucket // S
        ks, vs, logits = model.prefill(params, tokens, valid)
        # (L, 1, H, T, D) -> page-shaped (L, nbp, S, H, D)
        kr = jnp.transpose(ks[:, 0], (0, 2, 1, 3)).reshape(
            model.num_layers, nbp, S, model.num_heads, model.head_dim)
        vr = jnp.transpose(vs[:, 0], (0, 2, 1, 3)).reshape(
            model.num_layers, nbp, S, model.num_heads, model.head_dim)
        tok0 = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (1,)
        # pages leave this program at compute dtype; the page-write
        # program casts (or quantizes) into the pool's storage dtype
        return kr, vr, tok0

    def _admit_impl(self, params, kv, pt, tokens, ctx, padded, valid,
                    ids, row, slot, t, *, bucket):
        """The whole device side of one admission as ONE program:
        bucketed prompt prefill, page-pool scatter, and the slot-state
        commit (page-table row, first sampled token, context length).
        Admission used to cost ~5 eager dispatches; on CPU each eager
        scatter is a real millisecond, so fusing them is a measurable
        request-rate win."""
        kpag, vpag, tok0 = self._prefill_impl(params, padded, valid,
                                              bucket=bucket)
        kv = self.cache.write_pages(kv, kpag, vpag, ids)
        return (kv, pt.at[slot].set(row), tokens.at[slot].set(tok0[0]),
                ctx.at[slot].set(t), tok0)

    def _admit_fn(self, bucket):
        import jax

        fn = self._admit_fns.get(bucket)
        if fn is None:
            fn = self._admit_fns[bucket] = jax.jit(
                functools.partial(self._admit_impl, bucket=bucket),
                donate_argnums=(1, 2, 4))
        return fn

    def _admit_prep(self, slot, seq_id, prompt_tokens, max_new_tokens):
        """Host half of admission: validation, worst-case reservation,
        upfront allocation, and the padded/ids/row arrays the fused
        admit program consumes."""
        if self._host_active[slot] or slot in self._seq_of_slot:
            raise MXNetError("slot %d is occupied" % slot)
        prompt = np.array(list(prompt_tokens), np.int32)
        T = int(prompt.shape[0])
        total = T + int(max_new_tokens)
        if T < 1:
            raise MXNetError("empty prompt")
        if total > self.max_context:
            raise MXNetError(
                "prompt+max_new = %d exceeds the engine's max context %d"
                % (total, self.max_context))
        # slack covers speculative-verify overshoot past the budget
        if not self.cache.reserve(seq_id, total + self._reserve_slack):
            raise MXNetError("KV pool too busy for sequence %r (check "
                             "engine.can_admit before admitting)"
                             % (seq_id,))
        self._post_reserve(seq_id, total)
        bucket = self._bucket_for(T)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :T] = prompt
        self.cache.alloc_for(seq_id, T)
        pages = self.cache.pages_of(seq_id)
        nbp = bucket // self.cache.page_size
        ids = np.full((nbp,), self.cache.scratch_page, np.int32)
        n = min(len(pages), nbp)  # upfront-allocated tails stay put
        ids[:n] = pages[:n]  # bucket tail pages scatter to scratch
        row = self.cache.page_table_row(seq_id, self.table_width)
        return {"T": T, "bucket": bucket, "padded": padded, "ids": ids,
                "row": row, "prompt": prompt}

    def admit(self, slot, seq_id, prompt_tokens, max_new_tokens):
        """Prefill a request into a free slot: reserve its worst-case
        pages, then ONE fused dispatch runs the bucketed prompt pass,
        scatters the prompt K/V into the pool, and seeds the slot with
        the first sampled token. With a prefix index, a cached prefix
        routes through the fused SUFFIX program instead — shared pages
        enter the page table by reference and only the uncovered tail
        is computed.

        Returns a PendingValue of that first token — deferred like
        everything else; the scheduler materializes it at a retirement
        boundary (the prefill has certainly finished by then)."""
        import jax.numpy as jnp

        from ..ndarray.pending import PendingValue

        if self.prefix is not None:
            prompt = np.array(list(prompt_tokens), np.int32)
            pages, covered, chain = self.prefix.lookup(prompt)
            if covered:
                self.prefix.hit()
                return self._admit_with_prefix(
                    slot, seq_id, prompt, max_new_tokens,
                    pages, covered, chain)
            self.prefix.miss()
        p = self._admit_prep(slot, seq_id, prompt_tokens, max_new_tokens)
        try:
            kv, self._pt, self._tokens, self._ctx, tok0 = \
                self._admit_fn(p["bucket"])(
                    self.params, self.cache.state(), self._pt,
                    self._tokens, self._ctx, jnp.asarray(p["padded"]),
                    jnp.asarray(np.array([p["T"]], np.int32)),
                    jnp.asarray(p["ids"]), jnp.asarray(p["row"]),
                    np.int32(slot), np.int32(p["T"]))
        except Exception as e:  # noqa: BLE001 — OOM gets the HBM ledger
            from .. import diagnostics

            self.cache.free(seq_id)  # release the admission reservation
            diagnostics.reraise_if_oom(e, "serving_prefill")
            raise
        self.cache.swap(kv)
        self._seq_of_slot[slot] = seq_id
        self._host_active[slot] = True
        self._host_len[slot] = p["T"]
        _m.tokens_total().inc()  # the prefill-sampled first token
        if self.prefix is not None:
            self.prefix.register(p["prompt"],
                                 self.cache.pages_of(seq_id))
        return PendingValue(tok0)

    # -- shared-prefix admission ------------------------------------------
    @staticmethod
    def _pre_bucket(npre):
        """Prefix-gather page-count bucket (next power of two): bounds
        the number of fused suffix programs compiled per suffix
        bucket."""
        b = 1
        while b < npre:
            b *= 2
        return b

    def _prefix_admit_impl(self, params, kv, pt, tokens, ctx, padded,
                           valid, start, pre_ids, page_arr, slot_arr,
                           cow_src, cow_dst, row, slot, t, *, bucket,
                           pre_pages):
        """The whole device side of a prefix-HIT admission as ONE
        program: the copy-on-write page copy (a scratch self-copy when
        unused), the prefix page gather (+dequantization on quantized
        pools), the suffix prompt pass attending the reused prefix, a
        token-wise scatter of the suffix K/V into the sequence's own
        pages, and the slot-state commit."""
        import jax.numpy as jnp

        model = self.model
        S = self.cache.page_size
        # 1) COW: the diverging sequence's private copy of its last
        # shared page — BEFORE the gather, so a full-match admission
        # gathers its own copy
        kv = tuple(a.at[:, cow_dst].set(a[:, cow_src]) for a in kv)
        # 2) gather the reused prefix, dequantizing int8 pools back to
        # compute dtype (masked columns never contribute)
        kpre = kv[0][:, pre_ids]      # (L, preb, S, H, D)
        vpre = kv[1][:, pre_ids]
        if self.cache.quantized:
            kpre = kpre.astype(jnp.float32) \
                * (kv[2][:, pre_ids] * (1.0 / 127.0))[..., None]
            vpre = vpre.astype(jnp.float32) \
                * (kv[3][:, pre_ids] * (1.0 / 127.0))[..., None]
        else:
            kpre = kpre.astype(jnp.float32)
            vpre = vpre.astype(jnp.float32)
        L, H, D = model.num_layers, model.num_heads, model.head_dim
        kpre = kpre.reshape(L, pre_pages * S, H, D)
        vpre = vpre.reshape(L, pre_pages * S, H, D)
        # 3) suffix pass against the resident prefix
        ks, vs, logits = model.prefill_with_prefix(
            params, padded, valid, start, kpre, vpre)
        kr = jnp.transpose(ks[:, 0], (0, 2, 1, 3))  # (L, bucket, H, D)
        vr = jnp.transpose(vs[:, 0], (0, 2, 1, 3))
        # 4) ONE token-wise scatter of the suffix rows (padded tail
        # tokens route to the scratch page)
        if self.cache.quantized:
            kq, ka = self.cache._quantize(kr)
            vq, va = self.cache._quantize(vr)
            kv = (kv[0].at[:, page_arr, slot_arr].set(kq),
                  kv[1].at[:, page_arr, slot_arr].set(vq),
                  kv[2].at[:, page_arr, slot_arr].set(ka),
                  kv[3].at[:, page_arr, slot_arr].set(va))
        else:
            kv = (kv[0].at[:, page_arr, slot_arr].set(
                      kr.astype(kv[0].dtype)),
                  kv[1].at[:, page_arr, slot_arr].set(
                      vr.astype(kv[1].dtype)))
        tok0 = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (1,)
        return (kv, pt.at[slot].set(row), tokens.at[slot].set(tok0[0]),
                ctx.at[slot].set(t), tok0)

    def _prefix_admit_fn(self, bucket, pre_pages):
        import jax

        key = (bucket, pre_pages)
        fn = self._prefix_admit_fns.get(key)
        if fn is None:
            fn = self._prefix_admit_fns[key] = jax.jit(
                functools.partial(self._prefix_admit_impl,
                                  bucket=bucket, pre_pages=pre_pages),
                donate_argnums=(1, 2, 4))
        return fn

    def _admit_with_prefix(self, slot, seq_id, prompt, max_new_tokens,
                           pages, covered, chain):
        """Host half + dispatch of a prefix-hit admission: shared
        reservation (the cached pages join the page table by
        reference), the COW bookkeeping, suffix scatter coordinates,
        and the fused suffix program."""
        import jax.numpy as jnp

        from ..ndarray.pending import PendingValue

        if self._host_active[slot] or slot in self._seq_of_slot:
            raise MXNetError("slot %d is occupied" % slot)
        T = int(prompt.shape[0])
        total = T + int(max_new_tokens)
        if total > self.max_context:
            raise MXNetError(
                "prompt+max_new = %d exceeds the engine's max context %d"
                % (total, self.max_context))
        shared, cow, start = self._share_plan(T, pages, covered)
        if not self.cache.reserve(seq_id, total + self._reserve_slack,
                                  shared=shared, cow=cow):
            raise MXNetError("KV pool too busy for sequence %r (check "
                             "engine.can_admit before admitting)"
                             % (seq_id,))
        S = self.cache.page_size
        scratch = self.cache.scratch_page
        cow_src = cow_dst = scratch  # self-copy when no COW needed
        if cow:
            cow_src, cow_dst = self.cache.cow_page(seq_id,
                                                   len(shared) - 1)
        self.cache.alloc_for(seq_id, T)
        seq_pages = self.cache.pages_of(seq_id)
        Tsuf = T - start
        bucket = self._bucket_for(Tsuf)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :Tsuf] = prompt[start:]
        npre = -(-start // S)  # pages holding positions [0, start)
        preb = self._pre_bucket(npre)
        pre_ids = np.full((preb,), scratch, np.int32)
        pre_ids[:npre] = seq_pages[:npre]
        page_arr = np.full((bucket,), scratch, np.int32)
        slot_arr = np.zeros((bucket,), np.int32)
        for i in range(Tsuf):
            pos = start + i
            page_arr[i] = seq_pages[pos // S]
            slot_arr[i] = pos % S
        slot_arr[Tsuf:] = np.arange(bucket - Tsuf) % S  # scratch spread
        row = self.cache.page_table_row(seq_id, self.table_width)
        try:
            kv, self._pt, self._tokens, self._ctx, tok0 = \
                self._prefix_admit_fn(bucket, preb)(
                    self.params, self.cache.state(), self._pt,
                    self._tokens, self._ctx, jnp.asarray(padded),
                    jnp.asarray(np.array([Tsuf], np.int32)),
                    np.int32(start), jnp.asarray(pre_ids),
                    jnp.asarray(page_arr), jnp.asarray(slot_arr),
                    np.int32(cow_src), np.int32(cow_dst),
                    jnp.asarray(row), np.int32(slot), np.int32(T))
        except Exception as e:  # noqa: BLE001 — OOM gets the HBM ledger
            from .. import diagnostics

            self.cache.free(seq_id)  # drops the shared refs too
            diagnostics.reraise_if_oom(e, "serving_prefill")
            raise
        self.cache.swap(kv)
        self._seq_of_slot[slot] = seq_id
        self._host_active[slot] = True
        self._host_len[slot] = T
        _m.tokens_total().inc()  # the prefill-sampled first token
        self.prefix.register(prompt, seq_pages, chain)
        return PendingValue(tok0)

    def _post_reserve(self, seq_id, total):
        """Subclass hook: runs right after the admission reservation,
        before the prompt's pages allocate (the speculative engine
        materializes its full worst-case allocation here so the page
        table row is written complete, once)."""

    # -- recomposition ----------------------------------------------------
    def deactivate(self, slot):
        """Stop decoding a slot without releasing its pages (static
        batching's idle state; also the first half of release). A pure
        host flag flip — the mask ships with the next dispatch."""
        self._host_active[slot] = False

    def activate(self, slot):
        """Resume decoding a deactivated slot (its pages, context and
        current token were preserved). The speculative scheduler parks
        slots here while their budget is possibly complete in flight —
        a parked slot must NOT advance device-side, or tokens would be
        committed that the host never attributes."""
        if slot in self._seq_of_slot:
            self._host_active[slot] = True

    def release(self, slot):
        """Retire a slot: deactivate and free the sequence's pages and
        reservation. The stale page-table row stays — an inactive
        slot's reads are fully masked and its writes go to scratch, and
        the next admission overwrites the row — so recomposition costs
        zero device edits. In-flight steps still referencing the freed
        pages read the old pool *values* (dataflow), so this is safe
        mid-window."""
        self.deactivate(slot)
        seq = self._seq_of_slot.pop(slot, None)
        if seq is not None:
            self.cache.free(seq)
        self._host_len[slot] = 0

    # -- disaggregated prefill -> decode handoff --------------------------
    def export_pages(self, seq_id):
        """Materialize a resident sequence's KV pages as host arrays —
        the payload a PREFILL-role replica ships to a decode replica
        (serving/fleet.py srv_ship_pages). This is a deliberate
        device->host transfer: the handoff crosses the network, so the
        pages must become wire bytes here, exactly like the embedding
        store's row push — a serialization boundary, not a decode-loop
        sync."""
        pages = self.cache.pages_of(seq_id)
        ids = np.array(pages, np.int32)
        out = {
            "npages": len(pages),
            "quantized": self.cache.quantized,
            "k": np.asarray(self.cache.k_pages[:, ids]),  # sync-ok: handoff serialization boundary (wire payload)
            "v": np.asarray(self.cache.v_pages[:, ids]),  # sync-ok: handoff serialization boundary (wire payload)
        }
        if self.cache.quantized:
            out["ks"] = np.asarray(self.cache.k_scales[:, ids])  # sync-ok: handoff wire payload
            out["vs"] = np.asarray(self.cache.v_scales[:, ids])  # sync-ok: handoff wire payload
        return out

    def _adopt_impl(self, kv, pt, tokens, ctx, k_rows, v_rows, ks_rows,
                    vs_rows, ids, row, slot, t, tok0):
        """Install SHIPPED pages raw (already in pool storage dtype —
        no re-quantization, so adopted state is bit-identical to the
        prefill replica's) plus the slot-state commit, as one
        program."""
        kv0 = kv[0].at[:, ids].set(k_rows)
        kv1 = kv[1].at[:, ids].set(v_rows)
        if self.cache.quantized:
            kv = (kv0, kv1, kv[2].at[:, ids].set(ks_rows),
                  kv[3].at[:, ids].set(vs_rows))
        else:
            kv = (kv0, kv1)
        return (kv, pt.at[slot].set(row), tokens.at[slot].set(tok0),
                ctx.at[slot].set(t))

    def _adopt_fn(self, nbp):
        import jax

        fn = self._adopt_fns.get(nbp)
        if fn is None:
            fn = self._adopt_fns[nbp] = jax.jit(
                self._adopt_impl, donate_argnums=(0, 1, 3))
        return fn

    def adopt(self, slot, seq_id, prompt_len, max_new_tokens, payload,
              first_token):
        """Adopt a prefill replica's shipped KV pages into a free slot:
        reserve + allocate as a normal admission would, then ONE fused
        dispatch installs the page payload and commits the slot state.
        The request enters decode with ZERO prefill work here — its
        first sampled token (``first_token``) already rode the wire as
        a host int, so adoption returns nothing deferred."""
        import jax.numpy as jnp

        if self._host_active[slot] or slot in self._seq_of_slot:
            raise MXNetError("slot %d is occupied" % slot)
        T = int(prompt_len)
        total = T + int(max_new_tokens)
        if T < 1:
            raise MXNetError("empty prompt")
        if total > self.max_context:
            raise MXNetError(
                "prompt+max_new = %d exceeds the engine's max context %d"
                % (total, self.max_context))
        if bool(payload.get("quantized")) != self.cache.quantized:
            raise MXNetError("shipped pages are %squantized but this "
                             "pool is %squantized"
                             % ("" if payload.get("quantized") else "un",
                                "" if self.cache.quantized else "un"))
        if not self.cache.reserve(seq_id, total + self._reserve_slack):
            raise MXNetError("KV pool too busy for sequence %r (check "
                             "engine.can_admit before admitting)"
                             % (seq_id,))
        try:
            self.cache.alloc_for(seq_id, T)
            pages = self.cache.pages_of(seq_id)
            npages = int(payload["npages"])
            if npages != len(pages):
                raise MXNetError(
                    "shipped payload covers %d pages but a %d-token "
                    "prompt needs %d" % (npages, T, len(pages)))
            nbp = self._bucket_for(T) // self.cache.page_size
            ids = np.full((nbp,), self.cache.scratch_page, np.int32)
            ids[:npages] = pages

            def pad(a):
                if a.shape[1] == nbp:
                    return a
                w = np.zeros((a.shape[0], nbp) + a.shape[2:], a.dtype)
                w[:, :npages] = a
                return w

            args = [self.cache.state(), self._pt, self._tokens,
                    self._ctx, jnp.asarray(pad(payload["k"])),
                    jnp.asarray(pad(payload["v"]))]
            if self.cache.quantized:
                args += [jnp.asarray(pad(payload["ks"])),
                         jnp.asarray(pad(payload["vs"]))]
            else:
                z = np.zeros((1,), np.float32)
                args += [jnp.asarray(z), jnp.asarray(z)]  # unused
            row = self.cache.page_table_row(seq_id, self.table_width)
            args += [jnp.asarray(ids), jnp.asarray(row), np.int32(slot),
                     np.int32(T), np.int32(int(first_token))]
            kv, self._pt, self._tokens, self._ctx = \
                self._adopt_fn(nbp)(*args)
        except Exception as e:  # noqa: BLE001 — OOM gets the HBM ledger
            from .. import diagnostics

            self.cache.free(seq_id)
            diagnostics.reraise_if_oom(e, "serving_adopt")
            raise
        self.cache.swap(kv)
        self._seq_of_slot[slot] = seq_id
        self._host_active[slot] = True
        self._host_len[slot] = T
        _m.pages_adopted_total().inc(npages)

    def defrag(self):
        """Compact the KV pool and re-emit live slots' page-table rows
        against the moved page ids."""
        import jax.numpy as jnp

        moved = self.cache.defrag()
        if moved:
            for s, seq in self._seq_of_slot.items():
                self._pt = self._pt.at[s].set(jnp.asarray(
                    self.cache.page_table_row(seq, self.table_width)))
        return moved

    # -- AOT warm-start ---------------------------------------------------
    def aot_warmup(self):
        """Lower-and-compile every request-path program from live
        shapes: the decode step, each prefill bucket, and the page-write
        scatters. With MXT_COMPILE_CACHE_DIR set, a later replica
        replays all of it from disk — zero JIT on the request path."""
        import jax
        import jax.numpy as jnp

        def sds(a):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        i32 = jnp.int32
        pstruct = jax.tree_util.tree_map(sds, self.params)
        kv_sds = tuple(sds(a) for a in self.cache.state())
        n = 0
        self._jit_step.lower(
            pstruct, kv_sds, sds(self._ctx), sds(self._tokens),
            sds(self._pt),
            jax.ShapeDtypeStruct((self.slots,), i32)).compile()
        n += 1
        S = self.cache.page_size
        for bucket in list(self._buckets):
            self._admit_fn(bucket).lower(
                pstruct, kv_sds, sds(self._pt), sds(self._tokens),
                sds(self._ctx),
                jax.ShapeDtypeStruct((1, bucket), i32),
                jax.ShapeDtypeStruct((1,), i32),
                jax.ShapeDtypeStruct((bucket // S,), i32),
                jax.ShapeDtypeStruct((self.table_width,), i32),
                jax.ShapeDtypeStruct((), i32),
                jax.ShapeDtypeStruct((), i32)).compile()
            n += 1
        return n
