"""Continuous-batching request scheduler — the admission/recomposition
brain over :class:`~mxnet_tpu.serving.engine.DecodeEngine`.

The reference framework's serving story was ``Module.forward`` on a
padded batch: compose a batch, run it to the longest member's end, eat
the padding. Continuous batching (the vLLM/Orca discipline) recomposes
the batch at every decode step instead: finished requests retire
immediately, queued requests join mid-flight through a prefill, and the
fixed-slot decode program never idles a slot that traffic could fill.

Host/device split: the scheduler is PURE host bookkeeping. It learns
sampled tokens only when the engine's in-flight window retires them
(K steps per deferred read), so its view lags the device by up to K
steps — by design:

- length-based completion (``max_new_tokens``) is host-arithmetic and
  retires a slot the step its quota is dispatched (no lag);
- EOS-based completion is observed at retirement, so up to K post-EOS
  tokens are generated and discarded — the classic deferred-sync
  trade, same as the training guard flags;
- attribution is exact regardless of lag: every dispatched step carries
  its (slot → request) composition as window metadata, so a token row
  retiring after the slot was recomposed still lands on the right
  request.

Deadlines: a request carries an optional SLO budget (seconds from
``submit``); the scheduler evicts blown requests — queued or running —
frees their pages, and counts them in
``mxt_serving_requests_total{outcome="evicted"}``.

:class:`StaticBatcher` is the A/B baseline bench.py measures against:
same engine, same requests, but admission only at batch boundaries —
every slot waits for the batch's longest member, which is exactly the
waste continuous batching deletes.
"""
from __future__ import annotations

import collections
import itertools
import time

from ..base import MXNetError
from . import metrics as _m

__all__ = ["Request", "ContinuousBatcher", "StaticBatcher"]

_req_ids = itertools.count()


def _trace_span(req, name, t0, t1, now, **attrs):
    """Stamp one request-lifecycle span against the request's trace_id
    (a no-op for untraced requests). Host wall clocks only — the spans
    that depend on device results (prefill's first token, decode
    completion) are stamped from inside the engine window's EXISTING
    deferred retirement, so tracing adds zero device syncs."""
    if req.trace_id is None or t0 is None or t1 is None:
        return
    from .. import telemetry

    telemetry.record_trace_span(
        name, req.trace_id, t0, t1, clock_now=now,
        track=getattr(req, "_track", None), request=req.id, **attrs)


class Request:
    """One generation request: a prompt, a token budget, an optional
    deadline, and the output/latency record the scheduler fills in.
    ``trace_id`` (minted by the fleet router, or caller-supplied)
    threads the request through the distributed-tracing layer: the
    scheduler stamps queue/prefill/decode spans against it."""

    def __init__(self, prompt, max_new_tokens=16, deadline=None,
                 eos_id=None, request_id=None, trace_id=None,
                 tenant=None, priority=None):
        self.prompt = [int(t) for t in prompt]
        if not self.prompt:
            raise MXNetError("Request needs a non-empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        self.deadline = None if deadline is None \
            else float(deadline)  # sync-ok: host float, not a device read
        self.eos_id = None if eos_id is None else int(eos_id)
        self.id = request_id if request_id is not None \
            else "req-%d" % next(_req_ids)
        self.trace_id = None if trace_id is None else str(trace_id)
        # multi-tenant QoS (serving/qos.py): the tenant id rides for
        # accounting; the priority CLASS (lower = more important)
        # orders admission and selects preemption victims
        self.tenant = None if tenant is None else str(tenant)
        self.priority = 0 if priority is None else int(priority)
        self._track = None  # timeline row, stamped by the batcher
        # disaggregated handoff: a prefill replica already computed this
        # request's KV pages — (page payload, first token) to ADOPT at
        # admission instead of prefilling (serving/fleet.py ship/adopt)
        self._handoff = None
        self.output_tokens = []
        # queued|running|completed|evicted|rejected|preempted
        self.state = "created"
        self.t_submit = self.t_admit = self.t_first = self.t_finish = None
        self._dispatched = 0   # tokens generated-or-in-flight (incl. #1)
        self._first_pv = None  # deferred first token from prefill
        self._eos = False
        self._finalized = False
        # speculative (variable-advance) accounting: steps dispatched
        # but not yet retired, and the token-count UPPER bound they
        # imply (observed + inflight * k) — the dispatch gate that keeps
        # page usage within the admission reservation
        self._inflight = 0
        self._ub = 0

    @property
    def done(self):
        return self.state in ("completed", "evicted", "rejected",
                              "preempted")

    def _take_first(self, now):
        """Materialize the prefill's deferred first token (idempotent;
        one amortized host read per request). Stamps the prefill phase:
        submit-side wall clock to first-token availability."""
        pv, self._first_pv = self._first_pv, None
        if pv is None:
            return
        tok = int(pv.get().reshape(-1)[0])
        if self.t_first is None:
            self.t_first = now
            if self.t_admit is not None:
                _m.request_latency().labels("prefill").observe(
                    max(0.0, now - self.t_admit))
            # the prefill span closes here, inside the deferred read
            # that just materialized the first token — zero new syncs
            _trace_span(self, "prefill", self.t_admit, now, now)
        self._record(tok, now)

    def _record(self, tok, now):
        """One observed output token (post-EOS and over-budget tokens —
        dispatch lag artifacts — are discarded)."""
        if self.done and self.state != "completed":
            return
        if self._eos or len(self.output_tokens) >= self.max_new_tokens:
            return
        self.output_tokens.append(int(tok))
        if self.t_first is None:
            self.t_first = now
            _trace_span(self, "prefill", self.t_admit, now, now)
        if self.eos_id is not None and int(tok) == self.eos_id:
            self._eos = True
        if self._eos or len(self.output_tokens) >= self.max_new_tokens:
            self.state = "completed"
            self.t_finish = now
            # the decode-window span: first token -> last observed
            # token, closed inside the in-flight window's retirement
            _trace_span(self, "decode", self.t_first, now, now,
                        tokens=len(self.output_tokens))


class ContinuousBatcher:
    """Admission queue + per-step batch recomposition over one engine."""

    def __init__(self, engine, now_fn=time.monotonic, track=None):
        self.engine = engine
        # the timeline row traced requests' spans land on (a fleet
        # replica names this "replica-<i>"; standalone batchers group
        # under "batcher")
        self.track = str(track) if track is not None else "batcher"
        engine.on_tokens = self._on_tokens
        self._queue = collections.deque()
        self._slot_req = {}  # slot -> Request currently OWNING the slot
        self._now = now_fn
        self.steps = 0
        self.completed = []  # terminal requests, in finalization order
        # the hang watchdog observes decode progress: token retirements
        # bump the counter (in _on_tokens), outstanding work is queued +
        # running requests — a wedged decode (or a page leak starving
        # admission forever) shows as pending>0 with a frozen counter
        from .. import diagnostics

        diagnostics.register_source(
            "serving_decode",
            pending_fn=lambda: len(self._queue) + len(self._slot_req))
        self._diag = diagnostics

    # -- intake -----------------------------------------------------------
    def submit(self, request):
        """Queue a request (returns it). Requests that can NEVER fit —
        prompt+budget over the engine's context or the whole pool — are
        rejected immediately rather than deadlocking the queue."""
        request.t_submit = self._now()
        request._track = self.track
        total = len(request.prompt) + request.max_new_tokens
        # a speculative engine reserves extra overshoot pages per
        # sequence — impossibility is judged against the padded need
        padded = total + getattr(self.engine, "_reserve_slack", 0)
        cache = self.engine.cache
        if total > self.engine.max_context \
                or cache.pages_needed(padded) > cache.num_pages:
            request.state = "rejected"
            self._finalize(request, "rejected")
            return request
        request.state = "queued"
        self._queue.append(request)
        _m.queue_depth().set(len(self._queue))
        return request

    # -- the per-step recomposition loop ----------------------------------
    @property
    def _k(self):
        """Tokens one decode step may commit per slot (1 for the plain
        engine, draft_k for a speculative one)."""
        return int(getattr(self.engine, "tokens_per_step", 1) or 1)

    def _may_dispatch(self, req):
        """Whether a running request should ride the next decode step.
        Plain engines: stop once the whole budget is dispatched (each
        step is exactly one token). Speculative engines advance a slot
        by a device-side VARIABLE 1..k tokens the host only learns at
        retirement, so the gate is the upper bound: dispatch while even
        full acceptance of everything in flight could not finish the
        budget — this also caps context overshoot at one round past the
        budget, which is what the admission reservation slack covers."""
        if req.done:
            return False
        k = self._k
        if k <= 1:
            return req._dispatched < req.max_new_tokens
        return req._ub < req.max_new_tokens

    def step(self):
        """One scheduler tick: evict blown deadlines, retire finished
        slots, admit what fits, dispatch one decode step. Returns True
        while there is (or was) work."""
        now = self._now()
        self.steps += 1
        self._evict_deadlines(now)
        self._reap_finished(now)
        self._admit(now)
        meta = tuple((s, r) for s, r in sorted(self._slot_req.items())
                     if self._may_dispatch(r))
        k = self._k
        if k > 1:
            # a speculative engine advances EVERY device-active slot
            # each round, so the active mask must mirror the dispatch
            # set exactly: a gated slot left active would commit tokens
            # the host never attributes (silent stream corruption)
            dispatch = {s for s, _ in meta}
            for slot in self._slot_req:
                if slot in dispatch:
                    self.engine.activate(slot)
                else:
                    self.engine.deactivate(slot)
        if meta:
            self.engine.decode_step(meta=meta)
            for _, r in meta:
                r._dispatched += 1
                r._inflight += 1
                r._ub += k
        elif self._slot_req:
            # every occupied slot is gated on deferred results (budget
            # possibly complete): force the reads — the in-flight
            # window if rounds are pending, else the prefill-sampled
            # first token — so the host learns the true advances and
            # either finishes the requests or resumes dispatching
            if self.engine.window.pending:
                self.engine.flush()
            else:
                for req in list(self._slot_req.values()):
                    req._take_first(now)
                    req._ub = len(req.output_tokens) \
                        + req._inflight * self._k
                self._reap_finished(now)
        return bool(meta or self._queue or self._slot_req)

    def run(self, max_steps=100000):
        """Drive until the queue and every slot drain (or the step
        bound trips); flushes the window and returns ``completed``. An
        unhandled exception in the serve loop leaves a diagnostics
        post-mortem (when the layer is armed) before propagating."""
        try:
            while (self._queue or self._slot_req) \
                    and self.steps < int(max_steps):
                self.step()
            self.drain()
        except Exception as e:  # noqa: BLE001 — dump, then propagate
            self._diag.maybe_postmortem(
                "serve_loop:%s" % type(e).__name__)
            raise
        return self.completed

    def drain(self):
        """Barrier: retire every in-flight step, materialize pending
        first tokens, finalize what completed."""
        self.engine.flush()
        now = self._now()
        for r in list(self._slot_req.values()):
            r._take_first(now)
        self._reap_finished(now)

    def cancel(self, request):
        """Force-evict one request — queued or running — freeing its
        slot and pages: the fleet router's hedge-loser and
        drain-migration hook. Rides the deadline-eviction bookkeeping
        (same ``outcome="evicted"`` accounting, same mid-window safety:
        in-flight steps still attribute through their metadata and the
        late tokens are discarded). Idempotent; returns True when the
        request was live here."""
        if request.done:
            return False
        hit = False
        try:
            self._queue.remove(request)
            hit = True
        except ValueError:
            pass
        for slot, req in list(self._slot_req.items()):
            if req is request:
                self.engine.release(slot)
                del self._slot_req[slot]
                hit = True
        if not hit:
            return False
        request.state = "evicted"
        request.t_finish = self._now()
        self._finalize(request, "evicted")
        _m.queue_depth().set(len(self._queue))
        _m.active_requests().set(len(self._slot_req))
        return True

    # -- internals --------------------------------------------------------
    def _free_slots(self):
        return [s for s in range(self.engine.slots)
                if s not in self._slot_req]

    def _evict_deadlines(self, now):
        for slot, req in list(self._slot_req.items()):
            if req.deadline is not None and not req.done \
                    and now - req.t_submit > req.deadline:
                req.state = "evicted"
                req.t_finish = now
                self.engine.release(slot)
                del self._slot_req[slot]
                self._finalize(req, "evicted")
        kept = collections.deque()
        while self._queue:
            req = self._queue.popleft()
            if req.deadline is not None \
                    and now - req.t_submit > req.deadline:
                req.state = "evicted"
                req.t_finish = now
                self._finalize(req, "evicted")
            else:
                kept.append(req)
        self._queue = kept
        _m.queue_depth().set(len(self._queue))
        _m.active_requests().set(len(self._slot_req))

    def _quota_done(self, req):
        """Slot-release test. Plain engines may release the slot the
        step the budget is DISPATCHED (1 token/step — the tail rows
        attribute through metadata). A speculative slot's advance is
        variable, so only observed completion releases it."""
        if req.done:
            return True
        return self._k <= 1 and req._dispatched >= req.max_new_tokens

    def _reap_finished(self, now):
        """Release slots whose request finished — by observed completion
        (EOS) or by dispatch quota (every budgeted token is at least in
        flight; the remaining rows attribute through step metadata)."""
        for slot, req in list(self._slot_req.items()):
            if self._quota_done(req):
                req._take_first(now)  # covers max_new_tokens == 1
                self.engine.release(slot)
                del self._slot_req[slot]
                if req.done:
                    self._finalize(req, req.state)
                # else: quota dispatched, tail tokens still in flight —
                # completion lands via step metadata at retirement
        _m.active_requests().set(len(self._slot_req))

    def _pick_admit_index(self):
        """Index of the next queued request to admit: the best (lowest)
        priority class, FIFO within a class — so an interactive arrival
        overtakes queued bulk, but never an older interactive one. With
        uniform priorities (the no-QoS deployment) this is index 0,
        identical to the historical pure-FIFO admit."""
        best_i = 0
        best_p = self._queue[0].priority
        for i, req in enumerate(self._queue):
            if req.priority < best_p:
                best_i, best_p = i, req.priority
        return best_i

    def _preempt_for(self, req, now):
        """Free capacity for ``req`` by force-evicting one RUNNING
        victim of a strictly worse (higher-numbered) priority class —
        most-bulk first, latest-submitted within a class (least sunk
        work). The victim leaves through the deadline-eviction
        machinery but in its own ``preempted`` state, which the fleet
        router treats as non-terminal: the copy re-enqueues through the
        PR 11 idempotent-failover path and replays token-exact later —
        late, never lost. Returns True when a victim was evicted."""
        victims = [(s, r) for s, r in self._slot_req.items()
                   if not r.done and r.priority > req.priority]
        if not victims:
            return False
        victims.sort(key=lambda sr: (sr[1].priority,
                                     sr[1].t_submit or 0.0))
        slot, victim = victims[-1]
        self.engine.release(slot)
        del self._slot_req[slot]
        victim.state = "preempted"
        victim.t_finish = now
        self._finalize(victim, "preempted")
        _m.tenant_preempted_total().labels(
            victim.tenant or "default").inc()
        _m.active_requests().set(len(self._slot_req))
        return True

    def _admit(self, now):
        while self._queue:
            i = self._pick_admit_index()
            req = self._queue[i]
            if not self._free_slots():
                # slot pressure: a top-class arrival may preempt a
                # strictly lower class out of its slot; equal-priority
                # traffic waits exactly as before
                if not self._preempt_for(req, now):
                    break
                continue  # re-evaluate with the freed slot/pages
            total = len(req.prompt) + req.max_new_tokens
            # a handoff request adopts shipped pages — no prefix
            # discount applies, so gate on the plain reservation
            prompt = None if req._handoff is not None else req.prompt
            if not self.engine.can_admit(total, prompt=prompt):
                # page pressure: same preemption rule as slot pressure
                if not self._preempt_for(req, now):
                    break  # pages busy; retiring traffic will free them
                continue
            del self._queue[i]
            slot = self._free_slots()[0]
            req.t_admit = now
            _m.request_latency().labels("queue").observe(
                max(0.0, now - req.t_submit))
            _trace_span(req, "queue", req.t_submit, now, now)
            if req._handoff is not None:
                # disaggregated path: install the prefill replica's
                # shipped pages; the first token rode the wire as a
                # host int — zero prefill work, nothing deferred
                payload, tok0 = req._handoff
                self.engine.adopt(slot, req.id, len(req.prompt),
                                  req.max_new_tokens, payload, tok0)
                req._handoff = None
                req._first_pv = None
                req.state = "running"
                req._record(int(tok0), now)  # may complete a 1-budget
            else:
                req._first_pv = self.engine.admit(
                    slot, req.id, req.prompt, req.max_new_tokens)
                req.state = "running"
            req._dispatched = 1  # the prefill-sampled token
            req._inflight = 0
            req._ub = 1
            self._slot_req[slot] = req
        _m.queue_depth().set(len(self._queue))
        _m.active_requests().set(len(self._slot_req))

    def _on_tokens(self, step_no, row, meta):
        """Engine retirement callback: one host token row + the step's
        composition metadata. Runs inside the window's deferred read —
        records only; slot recomposition stays in step(). The engine
        decodes the row (a speculative round carries a variable-length
        accepted prefix per slot; the plain engine exactly one token)."""
        del step_no
        self._diag.progress("serving_decode")
        now = self._now()
        k = self._k
        for slot, req in (meta or ()):
            req._take_first(now)
            was_done = req.done
            for tok in self.engine.decode_row(row, slot):
                req._record(int(tok), now)
            req._inflight = max(0, req._inflight - 1)
            req._ub = len(req.output_tokens) + req._inflight * k
            if req.state == "completed" and not was_done:
                self._finalize(req, "completed")

    def _finalize(self, req, outcome):
        if req._finalized:
            return
        req._finalized = True
        _m.requests_total().labels(outcome).inc()
        if outcome in ("evicted", "rejected", "preempted"):
            now = self._now()
            _trace_span(req, outcome, req.t_submit,
                        req.t_finish if req.t_finish is not None
                        else now, now)
            # SLO misses ride the flight recorder: a post-mortem shows
            # WHICH requests were shed in the run-up to an incident
            self._diag.record_event(
                "request_" + outcome, request_id=req.id,
                prompt_tokens=len(req.prompt),
                max_new_tokens=req.max_new_tokens,
                deadline=req.deadline)
        if outcome == "completed" and req.t_first is not None \
                and req.t_finish is not None:
            _m.request_latency().labels("decode").observe(
                max(0.0, req.t_finish - req.t_first))
        self.completed.append(req)


class StaticBatcher(ContinuousBatcher):
    """The padded-batch baseline: admission happens ONLY at batch
    boundaries. A batch of mixed-length requests runs until its longest
    member finishes; short members' slots sit deactivated (no useful
    work, pages still held) — the cost continuous batching removes.
    Same engine, same requests, same metrics: bench.py's A/B."""

    def _admit(self, now):
        if self._slot_req:
            return  # batch in flight: the door is closed
        super()._admit(now)

    def _reap_finished(self, now):
        items = list(self._slot_req.items())
        if not items:
            return
        finished = []
        for slot, req in items:
            if self._quota_done(req):
                self.engine.deactivate(slot)  # idle, not released
                finished.append((slot, req))
        if len(finished) == len(items):  # batch boundary: release all
            super()._reap_finished(now)
        else:
            _m.active_requests().set(len(self._slot_req))
