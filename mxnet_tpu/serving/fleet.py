"""Serving fleet — membership-backed replica pool and replica lifecycle.

PR 7 built one serving replica; "millions of users" is a fleet of them
behind a front door. This module is the pool half of that front door
(the dispatch half is :mod:`~mxnet_tpu.serving.router`):

- **Registration.** Serving replicas REGISTER in the coordinator's
  :class:`~mxnet_tpu.membership.MembershipTable` under their own id
  namespace (``-(1<<20) - index`` — training workers own the
  non-negative ints, embedding servers the small negatives) with
  endpoint + capacity metadata riding the registration ``meta``,
  exactly like the PR 10 embedding servers. Heartbeat-backed liveness
  is therefore free: the coordinator's reaper fences a silent replica
  and the pool's death listener (``MembershipTable.add_death_listener``
  — the same hook the elastic reshard controller rides) feeds the
  router's failover scan.

- **Lifecycle.** A replica moves ``warming -> routable -> draining ->
  drained`` (or ``-> dead``). It is only marked routable AFTER its
  engine AOT-warms through ``tuning.warmup()``; with a shared
  ``MXT_COMPILE_CACHE_DIR`` a rejoining or hot-spare replica replays
  every request-path program from disk — rejoin never serves a cold
  compile (the PR 6 contract extended to fleet membership).

- **Fencing.** A replica the reaper declared dead may still be running
  (the zombie scenario): its late replies are refused with the typed
  :class:`StaleReplicaError` by the router's accept gate, never
  committed — the request has already failed over to a survivor.

- **Standalone role.** ``python -m mxnet_tpu.serving.fleet`` hosts one
  replica as its own process (the ``kvstore_server.py`` discipline):
  an async server answering ``srv_*`` ops (:class:`ServingHost`), a
  decode loop thread, and a membership registration at the coordinator
  carrying the endpoint so routers discover it.
  :class:`RemoteReplica` is the router-side handle for one.

Failure injection (``MXT_FAULT``): ``replica_kill:replica=I[,after=K]``
kills replica I at its Kth router tick (ungraceful — in-flight requests
fail over); ``replica_slow:replica=I,ms=N[,after=K]`` stalls replica
I's decode for N ms (hedge bait);
``replica_spawn_slow:ms=N`` (consulted by the autoscaler's spawn path)
holds a freshly spawned spare in ``warming`` for N ms — the router
must keep serving off the existing replicas meanwhile. All seeded and
deterministic.
"""
from __future__ import annotations

import os
import threading
import time

from ..base import MXNetError
from ..membership import StaleWorkerError, WorkerMembership
from ..resilience import KVStoreError
from . import metrics as _m
from .scheduler import ContinuousBatcher, Request

__all__ = [
    "StaleReplicaError", "LocalReplica", "RemoteReplica", "ReplicaPool",
    "ServingHost", "local_serving_fleet", "serve_replica",
]

# replica member-id namespace: training workers register non-negative
# ints, embedding servers -(index+1); serving replicas sit far below
# both so the three populations can share one coordinator table
_REPLICA_NS = 1 << 20

# lifecycle states
WARMING = "warming"
ROUTABLE = "routable"
DRAINING = "draining"
DRAINED = "drained"
DEAD = "dead"
_STATES = (WARMING, ROUTABLE, DRAINING, DRAINED, DEAD)


class StaleReplicaError(StaleWorkerError):
    """A reply arrived from a serving replica that was fenced (reaped by
    the membership coordinator, killed, or replaced): the router refuses
    to commit it — the request has been (or will be) re-dispatched onto
    a survivor, and a zombie's late tokens must never race that."""


def _replica_member_id(index):
    return -(_REPLICA_NS + int(index))


def _replica_index(member_id):
    return -int(member_id) - _REPLICA_NS


def _is_replica_member(member_id):
    try:
        return int(member_id) <= -_REPLICA_NS
    except (TypeError, ValueError):
        return False


def _payload_bytes(payload):
    """Wire size of a page payload — host shape metadata only."""
    return sum(int(a.nbytes) for a in payload.values()
               if hasattr(a, "nbytes"))


def _ship_prefill(engine, copy_id, prompt, max_new_tokens,
                  trace_id=None, track=None, now_fn=time.monotonic):
    """The prefill half of a disaggregated handoff: run the bucketed
    prefill HERE (a transient engine slot), export the finished KV
    pages + the prefill-sampled first token as a wire payload, and
    release the slot — the pages live on only in the payload (and, with
    a prefix index on this engine, as shared pages for later hits).
    Returns ``(tok0, payload)``; stamps a ``prefill`` span on
    ``trace_id``."""
    from .. import telemetry

    t0 = now_fn()
    slot = next((s for s in range(engine.slots)
                 if s not in engine._seq_of_slot), None)
    if slot is None:
        raise MXNetError("no free prefill slot for handoff %r"
                         % (copy_id,))
    seq_id = "ship:%s" % (copy_id,)
    pv = engine.admit(slot, seq_id, prompt, max_new_tokens)
    # sync-ok: handoff serialization boundary — the first token must
    # become a wire int here, one read per shipped request
    tok0 = int(pv.get().reshape(-1)[0])
    payload = engine.export_pages(seq_id)
    engine.release(slot)
    _m.pages_shipped_total().inc(payload["npages"])
    _m.ship_bytes_total().labels("ship").inc(_payload_bytes(payload))
    if trace_id is not None:
        t1 = now_fn()
        telemetry.record_trace_span(
            "prefill", trace_id, t0, t1, clock_now=t1, track=track,
            copy=copy_id, pages=payload["npages"])
    return tok0, payload


_SHIP_CACHE_CAP = 64  # idempotent re-ship window per replica


def _remember_ship(cache, copy_id, result):
    cache[copy_id] = result
    while len(cache) > _SHIP_CACHE_CAP:
        cache.pop(next(iter(cache)))


class LocalReplica:
    """One in-process serving replica: engine + continuous batcher +
    membership registration, with the drain/rejoin/kill lifecycle the
    router drives. The handle interface (``load``/``submit_copy``/
    ``cancel_copy``/``poll``/``tick``) is shared with
    :class:`RemoteReplica` so the router never cares which it holds."""

    def __init__(self, index, engine_factory, coordinator=None,
                 now_fn=time.monotonic, heartbeats=True, reg_timeout=5.0,
                 role="decode"):
        self.index = int(index)
        self._factory = engine_factory
        self.coordinator = coordinator
        self._now = now_fn
        self._heartbeats = bool(heartbeats)
        self._reg_timeout = reg_timeout
        # disaggregation: a "prefill" replica runs bucketed prefills
        # and ships finished KV pages; a "decode" replica adopts them
        # (every replica can still do both — the role is the router's
        # placement hint, carried on the membership meta)
        self.role = str(role)
        self.engine = None
        self.batcher = None
        self.member = None
        self.generation = None
        self.capacity = 0
        self.state = WARMING
        self.killed = False
        self.slow_until = 0.0   # replica_slow brownout horizon
        self._ticks = 0
        self._ships = 0         # ship_pages calls (chaos counter)
        self._copies = {}       # copy_id -> Request live on this replica
        self._shipped = {}      # copy_id -> (tok0, payload): re-ship cache
        self._poll_cursor = 0   # read cursor into batcher.completed

    # -- lifecycle ---------------------------------------------------------
    @property
    def alive(self):
        return self.state in (ROUTABLE, DRAINING)

    @property
    def fenced(self):
        """True when this replica's membership credential is no longer
        the live one (killed, reaped, or replaced): the router's accept
        gate refuses its replies typed."""
        m = self.member
        return self.killed or (m is not None and m.fenced)

    def start(self, warm=True):
        """Build the engine, AOT-warm it through ``tuning.warmup()``
        (zero request-path compiles with a warm persistent cache),
        register in the coordinator's membership table, and only THEN
        become routable — a cold replica is never offered traffic.
        Split as :meth:`prepare` + :meth:`go_routable` so the
        autoscaler can hold a slow-warming spare in ``warming``
        (``replica_spawn_slow``) without stalling the router."""
        self.prepare(warm=warm)
        return self.go_routable()

    def prepare(self, warm=True):
        """The hot-spare half of :meth:`start`: build + AOT-warm the
        engine WITHOUT registering. The replica stays ``warming`` — it
        joins membership (and traffic) only at :meth:`go_routable`."""
        self.state = WARMING
        self.killed = False
        self.slow_until = 0.0
        self._copies.clear()
        self._shipped.clear()
        self._ships = 0
        self._poll_cursor = 0
        self.engine = self._factory()
        self.capacity = int(self.engine.slots)
        self.batcher = ContinuousBatcher(self.engine, now_fn=self._now,
                                         track="replica-%d" % self.index)
        if warm:
            from .. import tuning

            tuning.warmup(steps=(self.engine,), kernels=False,
                          include_live=False, reason="fleet_replica")
        return self

    def go_routable(self):
        """Register and become routable (idempotent once routable)."""
        if self.state == ROUTABLE:
            return self
        if self.engine is None:
            raise MXNetError(
                "replica %d has no engine: call prepare() (or start()) "
                "before go_routable()" % self.index)
        self._register()
        self.state = ROUTABLE
        from .. import diagnostics

        diagnostics.record_event("fleet_replica_routable",
                                 replica=self.index,
                                 slots=self.capacity)
        return self

    def _register(self):
        if self.coordinator is None:
            return
        self.member = WorkerMembership(
            self.coordinator[0], self.coordinator[1],
            _replica_member_id(self.index), timeout=self._reg_timeout)
        self.member.register(meta={
            "serving_replica": True, "index": self.index,
            "slots": int(self.engine.slots), "endpoint": None,
            "role": self.role})
        if self._heartbeats:
            self.member.start_heartbeats()
        self.generation = self.member.generation

    def kill(self):
        """Ungraceful death (SIGKILL emulation): heartbeats silently
        stop, nothing deregisters, in-flight requests are stranded —
        exactly what the reaper + the router's failover must absorb."""
        if self.state == DEAD:
            return
        self.killed = True
        self.state = DEAD
        if self.member is not None:
            self.member.stop(deregister=False)

    def mark_dead(self):
        """The pool observed this replica dead (reaper listener or
        transport failure): same terminal state as :meth:`kill`."""
        self.kill()

    def drain_start(self):
        if self.alive:
            self.state = DRAINING

    def finish_drain(self):
        """Complete a drain: flush the engine window (every in-flight
        step's tokens delivered), then deregister gracefully — bounded,
        so a dead coordinator cannot park the drain (membership.py's
        best-effort deregister deadline)."""
        if self.batcher is not None:
            self.batcher.drain()
        if self.member is not None:
            self.member.stop(deregister=True)
            self.member = None
        self.generation = None
        self.state = DRAINED
        from .. import diagnostics

        diagnostics.record_event("fleet_replica_drained",
                                 replica=self.index)

    def rejoin(self, warm=True, fresh_engine=True):
        """Rejoin after a drain or death: rebuild (by default a FRESH
        engine — the hot-spare shape), AOT-warm through the shared
        compile cache, re-register under a fresh generation, and only
        then serve again."""
        if self.state not in (DRAINED, DEAD):
            raise MXNetError(
                "replica %d cannot rejoin from state %r (drain or kill "
                "it first)" % (self.index, self.state))
        if self.member is not None:   # killed: stop the old session
            self.member.stop(deregister=False)
            self.member = None
        if not fresh_engine and self.engine is not None:
            old_engine = self.engine
            factory, self._factory = self._factory, lambda: old_engine
            try:
                return self.start(warm=warm)
            finally:
                self._factory = factory
        return self.start(warm=warm)

    def close(self):
        if self.member is not None:
            self.member.stop(deregister=not self.killed)
            self.member = None

    # -- the router-facing handle interface --------------------------------
    def load(self):
        """Queue-depth / active-slot / capacity gauges the router's
        load-aware pick dispatches on (the same quantities
        serving/metrics.py exports)."""
        if not self.alive:
            raise ConnectionError(
                "serving replica %d is %s" % (self.index, self.state))
        return {"queue": len(self.batcher._queue),
                "active": len(self.batcher._slot_req),
                "slots": self.capacity}

    def submit_copy(self, copy_id, prompt, max_new_tokens, deadline=None,
                    eos_id=None, trace_id=None, tenant=None,
                    priority=None):
        """Dispatch one request copy into this replica's batcher.
        Returns the copy's admission state (``queued`` or — for a
        request that can never fit this engine — ``rejected``).
        ``trace_id`` threads the router's distributed trace through
        this replica's queue/prefill/decode spans; ``tenant`` /
        ``priority`` carry the QoS class into the batcher's
        priority-aware admission."""
        if not self.alive:
            raise ConnectionError(
                "serving replica %d is %s" % (self.index, self.state))
        req = Request(prompt, max_new_tokens=max_new_tokens,
                      deadline=deadline, eos_id=eos_id,
                      request_id=copy_id, trace_id=trace_id,
                      tenant=tenant, priority=priority)
        self.batcher.submit(req)
        if req.state == "rejected":
            return "rejected"
        self._copies[copy_id] = req
        return req.state

    def cancel_copy(self, copy_id):
        """Evict one copy (queued or running) through the scheduler's
        cancel hook — the hedge-loser / drain-migration path."""
        req = self._copies.get(copy_id)
        if req is not None:
            self.batcher.cancel(req)

    def ship_pages(self, copy_id, prompt, max_new_tokens, trace_id=None):
        """PREFILL-role half of a disaggregated handoff: prefill the
        prompt here and return ``(first_token, page_payload)`` for
        adoption on a decode replica. Idempotent by copy id — a
        transport retry re-ships the cached payload instead of
        re-prefilling. Consults the seeded ``replica_kill`` rule first
        so chaos cells can kill a prefill replica deterministically
        MID-SHIP (the router's kv_retry re-routes to a survivor or
        falls back to local prefill)."""
        from .. import resilience

        if not self.alive:
            raise ConnectionError(
                "serving replica %d is %s" % (self.index, self.state))
        cached = self._shipped.get(copy_id)
        if cached is not None:
            return cached
        inj = resilience.fault_point()
        rule = inj.rule("replica_kill")
        if rule is not None \
                and int(rule.get("replica", -1)) == self.index \
                and self._ships >= int(rule.get("after", 0)) \
                and inj.should("replica_kill"):
            self.kill()
            raise ConnectionError(
                "serving replica %d died mid-ship" % self.index)
        self._ships += 1
        out = _ship_prefill(self.engine, copy_id, prompt,
                            max_new_tokens, trace_id=trace_id,
                            track="replica-%d" % self.index,
                            now_fn=self._now)
        _remember_ship(self._shipped, copy_id, out)
        return out

    def adopt_copy(self, copy_id, prompt, max_new_tokens, deadline=None,
                   eos_id=None, trace_id=None, handoff=None,
                   tenant=None, priority=None):
        """DECODE-role half of a disaggregated handoff: submit a
        request whose KV pages (and first token) were prefilled
        elsewhere — the scheduler installs the payload at admission and
        the request enters decode with zero prefill work here.
        Idempotent by copy id."""
        if not self.alive:
            raise ConnectionError(
                "serving replica %d is %s" % (self.index, self.state))
        if copy_id in self._copies:  # idempotent re-adopt
            return self._copies[copy_id].state
        tok0, payload = handoff
        req = Request(prompt, max_new_tokens=max_new_tokens,
                      deadline=deadline, eos_id=eos_id,
                      request_id=copy_id, trace_id=trace_id,
                      tenant=tenant, priority=priority)
        req._handoff = (payload, int(tok0))
        self.batcher.submit(req)
        if req.state == "rejected":
            return "rejected"
        _m.ship_bytes_total().labels("adopt").inc(
            _payload_bytes(payload))
        self._copies[copy_id] = req
        return req.state

    def queued_copies(self):
        """Copy ids still admission-queued here (migratable on drain)."""
        return [cid for cid, r in self._copies.items()
                if r.state == "queued"]

    def poll(self):
        """Newly finalized copies as ``(copy_id, state, tokens)``."""
        out = []
        if self.batcher is None:
            return out
        done = self.batcher.completed
        while self._poll_cursor < len(done):
            r = done[self._poll_cursor]
            self._poll_cursor += 1
            if r.id in self._copies:
                del self._copies[r.id]
                out.append((r.id, r.state, list(r.output_tokens)))
        return out

    def pending(self):
        return self.batcher is not None and bool(
            self.batcher._queue or self.batcher._slot_req)

    def tick(self, now=None):
        """One co-operative scheduler tick (the router's step drives
        every in-process replica). Consults the seeded ``replica_kill``
        / ``replica_slow`` fault rules first so chaos cells are
        deterministic; a browned-out replica makes no decode progress
        until its stall horizon passes (hedge bait)."""
        from .. import resilience

        if self.state in (DEAD, DRAINED):
            return False
        now = self._now() if now is None else now
        inj = resilience.fault_point()
        rule = inj.rule("replica_kill")
        if rule is not None \
                and int(rule.get("replica", -1)) == self.index \
                and self._ticks >= int(rule.get("after", 0)) \
                and inj.should("replica_kill"):
            self.kill()
            return False
        rule = inj.rule("replica_slow")
        if rule is not None \
                and int(rule.get("replica", -1)) == self.index \
                and self._ticks >= int(rule.get("after", 0)) \
                and inj.should("replica_slow"):
            self.slow_until = now + \
                float(rule.get("ms", 50.0)) / 1e3  # sync-ok: host rule param
        self._ticks += 1
        if now < self.slow_until:
            return False
        if self.pending():
            self.batcher.step()
            return True
        if self._copies:
            # idle but copies undelivered: their tail tokens are still
            # riding the deferred window — drain it so completions land
            # now instead of at the fleet-wide flush (the amortized
            # window stays intact while the replica is busy)
            self.batcher.drain()
        return False

    def flush(self):
        """Drain the engine's in-flight window (deferred tokens land)."""
        if self.batcher is not None and self.state not in (DEAD,):
            self.batcher.drain()


class RemoteReplica:
    """Router-side handle for a standalone replica process
    (:func:`serve_replica`): the same interface as :class:`LocalReplica`
    but every call is one ``srv_*`` op over the authenticated async
    transport. The remote process drives its own decode loop, so
    :meth:`tick` is a no-op here."""

    def __init__(self, index, host, port, slots=None, timeout=None,
                 role="decode"):
        from .. import config
        from ..async_server import AsyncClient

        self.index = int(index)
        self.host = host
        self.port = int(port)
        self.capacity = int(slots or 0)
        self.role = str(role)
        self.state = ROUTABLE
        self.killed = False
        self.generation = None
        self.member = None
        self.slow_until = 0.0
        self.batcher = None
        t = timeout if timeout is not None else config.get(
            "MXT_KV_DEADLINE")
        self._cl = AsyncClient(host, self.port,
                               timeout=float(t))  # sync-ok: host config scalar

    @property
    def alive(self):
        return self.state in (ROUTABLE, DRAINING)

    @property
    def fenced(self):
        return self.killed

    def load(self):
        ld = self._cl.request("srv_load")
        if not self.capacity:
            self.capacity = int(ld.get("slots", 0))
        return ld

    def submit_copy(self, copy_id, prompt, max_new_tokens, deadline=None,
                    eos_id=None, trace_id=None, tenant=None,
                    priority=None):
        # the trace_id rides the srv_submit frame, so the remote
        # replica's queue/prefill/decode spans land in ITS span log
        # under the router's trace — the collector's tel_spans scrape
        # reunites them; tenant/priority extend the frame (old hosts
        # read the 6-tuple prefix, new hosts default missing QoS fields)
        return self._cl.request(
            "srv_submit", None,
            (copy_id, [int(t) for t in prompt], int(max_new_tokens),
             deadline, eos_id, trace_id, tenant, priority))

    def ship_pages(self, copy_id, prompt, max_new_tokens, trace_id=None):
        # page payloads (numpy arrays) ride the pickle frame whole —
        # the serving twin of the embedding store's batched row push
        tok0, payload = self._cl.request(
            "srv_ship_pages", None,
            (copy_id, [int(t) for t in prompt], int(max_new_tokens),
             trace_id))
        return int(tok0), payload

    def adopt_copy(self, copy_id, prompt, max_new_tokens, deadline=None,
                   eos_id=None, trace_id=None, handoff=None,
                   tenant=None, priority=None):
        return self._cl.request(
            "srv_adopt_pages", None,
            (copy_id, [int(t) for t in prompt], int(max_new_tokens),
             deadline, eos_id, trace_id, handoff, tenant, priority))

    def cancel_copy(self, copy_id):
        self._cl.request("srv_cancel", None, copy_id)

    def queued_copies(self):
        return list(self._cl.request("srv_queued"))

    def poll(self):
        return [tuple(x) for x in self._cl.request("srv_poll")]

    def pending(self):
        ld = self.load()
        return bool(ld.get("queue") or ld.get("active"))

    def tick(self, now=None):
        return False  # the remote process self-drives its decode loop

    def flush(self):
        pass

    def drain_start(self):
        if self.alive:
            self.state = DRAINING
            try:
                self._cl.request("srv_drain", None, True)
            except (KVStoreError, ConnectionError, OSError):
                pass

    def finish_drain(self):
        self.state = DRAINED

    def kill(self):
        if self.state == DEAD:
            return
        self.killed = True
        self.state = DEAD
        self._cl.close()

    def mark_dead(self):
        self.kill()

    def rejoin(self, warm=True, **kw):
        raise MXNetError(
            "a RemoteReplica rejoins from its own process (restart it; "
            "it re-registers and re-warms itself before serving)")

    def close(self):
        self._cl.close()


class ReplicaPool:
    """The router's view of the fleet: handles by replica index, the
    load-aware pick, and death intake from the coordinator's membership
    reaper (the same ``add_death_listener`` hook the elastic reshard
    controller uses — listener callbacks run on the reaper thread, so
    they only RECORD here; the router applies them at its next step)."""

    def __init__(self, coordinator=None, server=None):
        self.coordinator = coordinator
        self.server = server  # in-process coordinator AsyncParamServer
        self._handles = {}
        self._lock = threading.Lock()
        self._dead_pending = []
        if server is not None:
            server.membership.add_death_listener(self._on_deaths)

    # -- membership --------------------------------------------------------
    def add(self, handle):
        self._handles[handle.index] = handle
        self.publish()
        return handle

    def get(self, rid):
        return self._handles[rid]

    def replicas(self):
        return [self._handles[k] for k in sorted(self._handles)]

    def routable(self, role=None):
        out = [h for h in self.replicas()
               if h.state == ROUTABLE and not h.fenced]
        if role is not None:
            out = [h for h in out
                   if getattr(h, "role", "decode") == role]
        return out

    def total_capacity(self):
        return sum(int(h.capacity or 0) for h in self.replicas()
                   if h.state in (ROUTABLE, DRAINING))

    def pick(self, exclude=(), role=None):
        """Least-loaded routable replica — the SLO-aware placement
        rule: (queue depth + active slots) / capacity, ties broken by
        lowest index for determinism. A replica whose load probe fails
        is marked dead on the spot (transport-observed death).
        ``role`` restricts the candidates to one disaggregation tier
        (prefill/decode)."""
        best, best_score = None, None
        for h in self.routable(role):
            if h.index in exclude:
                continue
            try:
                ld = h.load()
            except (ConnectionError, OSError):
                self.mark_dead(h.index)
                continue
            slots = max(1, int(ld.get("slots") or h.capacity or 1))
            score = (int(ld.get("queue", 0))
                     + int(ld.get("active", 0))) / float(slots)  # sync-ok: host gauge arithmetic
            if best_score is None or score < best_score:
                best, best_score = h, score
        return best

    def _on_deaths(self, worker_ids):
        # reaper-thread callback: record only (never mutate handles or
        # touch telemetry from under the membership reaper)
        rids = [_replica_index(w) for w in worker_ids
                if _is_replica_member(w)]
        if rids:
            with self._lock:
                self._dead_pending.extend(rids)

    def poll_deaths(self):
        """Apply reaper-reported deaths; returns the replica ids newly
        marked dead this call."""
        with self._lock:
            rids, self._dead_pending = self._dead_pending, []
        out = []
        for rid in rids:
            h = self._handles.get(rid)
            if h is not None and h.state != DEAD:
                self.mark_dead(rid)
                out.append(rid)
        return out

    def mark_dead(self, rid):
        """This pool observed replica ``rid`` dead (reaper verdict or a
        transport failure mid-dispatch)."""
        h = self._handles.get(rid)
        if h is None or h.state == DEAD:
            return
        h.mark_dead()
        from .. import diagnostics

        diagnostics.record_event("fleet_replica_dead", replica=rid)
        self.publish()

    def refresh(self):
        """Reconcile with the coordinator's membership view: fence
        handles whose registration is gone/dead, and discover standalone
        replicas that registered an endpoint we have no handle for."""
        view = None
        if self.server is not None:
            view = self.server.membership.view()
        if view is None:
            return self
        dead = {_replica_index(w) for w in view.get("dead", {})
                if _is_replica_member(w)}
        live = {_replica_index(w) for w in view.get("members", {})
                if _is_replica_member(w)}
        for rid, h in list(self._handles.items()):
            if rid in dead and h.state not in (DEAD, DRAINED):
                self.mark_dead(rid)
        for w, meta in view.get("meta", {}).items():
            if not (_is_replica_member(w) and isinstance(meta, dict)
                    and meta.get("serving_replica")):
                continue
            rid = int(meta.get("index", _replica_index(w)))
            ep = meta.get("endpoint")
            if rid in live and rid not in self._handles and ep:
                self.add(RemoteReplica(
                    rid, ep[0], ep[1], slots=meta.get("slots"),
                    role=meta.get("role", "decode")))
        self.publish()
        return self

    def publish(self):
        """Export ``mxt_fleet_replicas{state}`` (mxt_top's fleet line)
        plus per-replica occupancy gauges (the router's load signal,
        published so the fleet collector's per-replica view needs no
        extra RPCs — in-process handles read their batcher directly; a
        dead/drained replica publishes 0 so its occupancy can never
        linger at a stale value in an aggregate)."""
        counts = {s: 0 for s in _STATES}
        occ = _m.fleet_replica_occupancy()
        for h in self._handles.values():
            counts[h.state] = counts.get(h.state, 0) + 1
            b = getattr(h, "batcher", None)
            if h.state in (DEAD, DRAINED) or b is None:
                occ.labels(str(h.index)).set(0)
            else:
                occ.labels(str(h.index)).set(len(b._slot_req))
        g = _m.fleet_replicas()
        for s, n in counts.items():
            g.labels(s).set(n)

    def close(self):
        for h in self.replicas():
            try:
                h.close()
            except Exception:  # noqa: BLE001 — teardown best effort
                pass


def local_serving_fleet(n, engine_factory, now_fn=time.monotonic,
                        warm=True, heartbeats=True, roles=None):
    """An in-process fleet: one coordinator async server (the membership
    table), ``n`` :class:`LocalReplica`\\ s registered in it over real
    loopback sockets, and the pool wired to the reaper's death listener.
    ``roles`` (optional, one per replica) assigns disaggregation tiers
    — e.g. ``("prefill", "decode", "decode")``. Returns
    ``(pool, coordinator_server)`` — close the pool's replicas, then
    the server (the order is forgiving: graceful deregister is
    bounded)."""
    from ..async_server import AsyncParamServer

    if n < 1:
        raise MXNetError("a serving fleet needs at least one replica")
    srv = AsyncParamServer("127.0.0.1", 0)
    coord = ("127.0.0.1", srv._sock.getsockname()[1])
    pool = ReplicaPool(coordinator=coord, server=srv)
    for i in range(n):
        role = roles[i] if roles else "decode"
        pool.add(LocalReplica(i, engine_factory, coordinator=coord,
                              now_fn=now_fn, heartbeats=heartbeats,
                              role=role).start(warm=warm))
    pool.publish()
    return pool, srv


# ---------------------------------------------------------------------------
# standalone replica role (the kvstore_server.py discipline)
# ---------------------------------------------------------------------------
class ServingHost:
    """Server-side ``srv_*`` op handler for a standalone replica:
    attached to an :class:`~mxnet_tpu.async_server.AsyncParamServer` via
    ``attach_serving``. One lock serializes op handling against the
    decode loop thread (the batcher is host bookkeeping, not
    thread-safe by itself)."""

    def __init__(self, batcher):
        self.batcher = batcher
        self.admitting = True
        self._copies = {}
        self._shipped = {}  # copy_id -> (tok0, payload): re-ship cache
        self._cursor = 0
        self._lock = threading.Lock()

    def handle(self, op, key, payload):
        del key
        with self._lock:
            if op == "srv_submit":
                if not self.admitting:
                    return ("err", "replica is draining (not admitting)")
                # pre-tracing routers send 5-tuples; the trace_id is
                # the optional 6th element, QoS tenant/priority the
                # optional 7th/8th (pre-QoS routers omit them)
                cid, prompt, max_new, deadline, eos = payload[:5]
                trace_id = payload[5] if len(payload) > 5 else None
                tenant = payload[6] if len(payload) > 6 else None
                priority = payload[7] if len(payload) > 7 else None
                req = Request(prompt, max_new_tokens=max_new,
                              deadline=deadline, eos_id=eos,
                              request_id=cid, trace_id=trace_id,
                              tenant=tenant, priority=priority)
                self.batcher.submit(req)
                if req.state == "rejected":
                    return ("ok", "rejected")
                self._copies[cid] = req
                return ("ok", req.state)
            elif op == "srv_cancel":
                req = self._copies.get(payload)
                if req is not None:
                    self.batcher.cancel(req)
                return ("ok", None)
            elif op == "srv_queued":
                return ("ok", [cid for cid, r in self._copies.items()
                               if r.state == "queued"])
            elif op == "srv_poll":
                out = []
                done = self.batcher.completed
                while self._cursor < len(done):
                    r = done[self._cursor]
                    self._cursor += 1
                    if r.id in self._copies:
                        del self._copies[r.id]
                        out.append((r.id, r.state,
                                    list(r.output_tokens)))
                return ("ok", out)
            elif op == "srv_load":
                return ("ok", {
                    "queue": len(self.batcher._queue),
                    "active": len(self.batcher._slot_req),
                    "slots": int(self.batcher.engine.slots)})
            elif op == "srv_ship_pages":
                # the disaggregated handoff's prefill half, served over
                # the wire: idempotent by copy id (a kv_retry re-ship
                # returns the cached payload without re-prefilling)
                if not self.admitting:
                    return ("err", "replica is draining (not admitting)")
                cid, prompt, max_new, trace_id = payload
                cached = self._shipped.get(cid)
                if cached is None:
                    cached = _ship_prefill(
                        self.batcher.engine, cid, prompt, max_new,
                        trace_id=trace_id, track=self.batcher.track)
                    _remember_ship(self._shipped, cid, cached)
                return ("ok", cached)
            elif op == "srv_adopt_pages":
                if not self.admitting:
                    return ("err", "replica is draining (not admitting)")
                cid, prompt, max_new, deadline, eos, trace_id, handoff \
                    = payload[:7]
                tenant = payload[7] if len(payload) > 7 else None
                priority = payload[8] if len(payload) > 8 else None
                if cid in self._copies:  # idempotent re-adopt
                    return ("ok", self._copies[cid].state)
                tok0, pl = handoff
                req = Request(prompt, max_new_tokens=max_new,
                              deadline=deadline, eos_id=eos,
                              request_id=cid, trace_id=trace_id,
                              tenant=tenant, priority=priority)
                req._handoff = (pl, int(tok0))
                self.batcher.submit(req)
                if req.state == "rejected":
                    return ("ok", "rejected")
                _m.ship_bytes_total().labels("adopt").inc(
                    _payload_bytes(pl))
                self._copies[cid] = req
                return ("ok", req.state)
            elif op == "srv_drain":
                self.admitting = not bool(payload)
                return ("ok", None)
        return ("err", "unknown serving op %r" % (op,))

    def step(self):
        """One decode-loop tick under the op lock; returns True when
        work was done (the loop thread backs off otherwise)."""
        with self._lock:
            if self.batcher._queue or self.batcher._slot_req:
                self.batcher.step()
                return True
            self.batcher.drain()
        return False

    def run_loop(self, stop_event, idle=0.005):
        while not stop_event.is_set():
            if not self.step():
                stop_event.wait(idle)


def serve_replica(engine, coordinator, index=0, host="127.0.0.1",
                  port=0, now_fn=time.monotonic, role="decode"):
    """Host one replica as a standalone server: binds an async server
    answering ``srv_*`` ops, AOT-warms the engine, registers at the
    ``coordinator`` membership table with the endpoint + capacity meta
    routers discover remotely, and starts the decode loop thread.
    Returns ``(server, host_obj, member, stop)`` — call ``stop()`` to
    drain the loop, deregister, and close."""
    from .. import tuning
    from ..async_server import AsyncParamServer

    srv = AsyncParamServer(host, port)
    bound = srv._sock.getsockname()
    batcher = ContinuousBatcher(engine, now_fn=now_fn,
                                track="replica-%d" % index)
    hostobj = ServingHost(batcher)
    srv.attach_serving(hostobj)
    tuning.warmup(steps=(engine,), kernels=False, include_live=False,
                  reason="fleet_replica")
    member = WorkerMembership(coordinator[0], coordinator[1],
                              _replica_member_id(index))
    member.register(meta={
        "serving_replica": True, "index": int(index),
        "slots": int(engine.slots),
        "endpoint": (bound[0], int(bound[1])),
        "role": str(role)})
    member.start_heartbeats()
    stop_event = threading.Event()
    loop = threading.Thread(target=hostobj.run_loop, args=(stop_event,),
                            daemon=True, name="fleet-replica-%d" % index)
    loop.start()

    def stop():
        stop_event.set()
        loop.join(timeout=5.0)
        member.stop(deregister=True)
        srv.close()

    return srv, hostobj, member, stop


def main():
    """``python -m mxnet_tpu.serving.fleet`` — demo standalone replica:
    a TinyDecoder engine (geometry via ``MXT_FLEET_MODEL=layers,heads,
    head_dim``) registered at ``MXT_FLEET_COORDINATOR=host:port`` under
    ``MXT_FLEET_REPLICA_ID``. Real deployments build their own engine
    and call :func:`serve_replica` directly."""
    coord = os.environ.get("MXT_FLEET_COORDINATOR")
    if not coord or ":" not in coord:
        raise MXNetError(
            "set MXT_FLEET_COORDINATOR=host:port (the membership "
            "coordinator the replica registers with)")
    chost, _, cport = coord.rpartition(":")
    geom = os.environ.get("MXT_FLEET_MODEL", "2,2,16").split(",")
    layers, heads, hdim = (int(x) for x in geom)
    index = int(os.environ.get("MXT_FLEET_REPLICA_ID", "0"))
    from .model import TinyDecoder
    from .engine import DecodeEngine

    model = TinyDecoder(vocab=512, num_layers=layers, num_heads=heads,
                        head_dim=hdim, max_len=512)
    eng = DecodeEngine(model, params=model.init_params(0))
    srv, _, _, stop = serve_replica(eng, (chost, int(cport)),
                                    index=index,
                                    port=int(os.environ.get(
                                        "MXT_FLEET_PORT", "0")),
                                    role=os.environ.get(
                                        "MXT_FLEET_ROLE", "decode"))
    print("SERVING_REPLICA_READY %s:%d"
          % srv._sock.getsockname()[:2], flush=True)
    try:
        while not srv._stop.wait(0.2):
            pass
    except KeyboardInterrupt:
        pass
    finally:
        stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
