"""Inference serving stack (ROADMAP direction 1 — the "millions of
users" front).

Everything before this PR served *training*; this package is the
production-traffic half:

- :mod:`~mxnet_tpu.serving.kv_cache` — :class:`PagedKVCache`: fixed-size
  KV pages in a preallocated device pool, per-request page tables,
  reservation-based admission, alloc/free/defrag.
- :mod:`~mxnet_tpu.serving.engine` — :class:`DecodeEngine`: ONE donated
  fixed-shape jit program per decode step (append K/V through the page
  table, ragged paged attention, greedy sample), zero per-step host
  syncs via ``engine.InflightWindow``, shape-bucketed prefill, and
  ``aot_warmup()`` so a warm replica pays zero request-path JIT.
- :mod:`~mxnet_tpu.serving.scheduler` — :class:`Request`,
  :class:`ContinuousBatcher` (admission, per-request deadlines, batch
  recomposition every step), and the :class:`StaticBatcher` A/B
  baseline.
- :mod:`~mxnet_tpu.serving.model` — the decode-model adapter protocol
  and :class:`TinyDecoder`, the pure-JAX causal LM the tests, bench,
  and examples drive.
- :mod:`~mxnet_tpu.serving.speculative` — :class:`SpeculativeEngine`:
  a cheap draft model proposes ``draft_k`` tokens per slot, the target
  verifies all of them in ONE wide launch (greedy token-exact by
  construction), acceptance committed device-side — two launches per
  round for up to k tokens. Compose with ``PagedKVCache(
  quantized=True)`` for int8 KV pages (~4x resident sequences per
  byte) and ``TinyDecoder.quantize_params`` for weight-only int8
  decode matmuls routed per shape by ``tuning.resolve_quant``.
- :mod:`~mxnet_tpu.serving.prefix` — :class:`PrefixIndex`:
  shared-prefix KV reuse. Prompts are hashed at admission in
  page-aligned chunks (a blake2b chain); a hit points the new
  sequence's page table at the already-resident pages (per-page
  refcounts in :class:`PagedKVCache`, copy-on-write on divergence)
  and prefill starts at the first non-shared token. Enable with
  ``DecodeEngine(..., prefix_cache=True)``.
- :mod:`~mxnet_tpu.serving.metrics` — SLO metrics
  (``mxt_serving_*``) through the PR-5 telemetry registry;
  ``tools/mxt_top.py`` renders them live.
- :mod:`~mxnet_tpu.serving.fleet` /
  :mod:`~mxnet_tpu.serving.router` — the fault-tolerant serving
  fleet: replicas REGISTER in a coordinator's membership table
  (heartbeat liveness, endpoint + capacity meta), an SLO-aware
  :class:`FleetRouter` dispatches load-aware with hedged retries,
  transparent failover on replica death (idempotency tokens — a
  replayed completed request never re-decodes), graceful drain +
  AOT-warm rejoin, and typed refusal of fenced zombies' late replies.
  Replicas may run role-split (``role="prefill"`` / ``"decode"``):
  long prompts prefill on the prefill tier, the finished KV pages
  ship over the transport (``srv_ship_pages`` / ``srv_adopt_pages``)
  and the request enters decode with zero prefill work on the decode
  tier.
- :mod:`~mxnet_tpu.serving.autoscaler` /
  :mod:`~mxnet_tpu.serving.qos` — the closed control loop over all of
  it: :class:`FleetAutoscaler` consumes the FleetCollector's merged
  fleet page (p99 vs SLO, queue depth, occupancy, goodput) and
  actuates — spawns AOT-warm spares through the warming->routable
  lifecycle, shrinks via ``router.drain``, independently scales
  decode-worker fleets and the prefill/decode tiers — with hysteresis
  + cooldown (never flaps) and typed floor refusal
  (:class:`AutoscalerError`); :class:`QosPolicy` adds multi-tenant
  isolation — per-tenant outstanding quotas with typed
  :class:`OverQuotaError` refusal, priority-class dispatch, and
  preemption of bulk for interactive (preempted requests re-enqueue
  idempotently, never lost). :class:`TrafficGenerator` is the seeded
  flash-crowd arrival process the chaos cells drive.

Minimal use::

    from mxnet_tpu import serving

    model = serving.TinyDecoder(vocab=512, num_layers=2)
    eng = serving.DecodeEngine(model, slots=8)
    eng.aot_warmup()                      # or tuning.warmup()
    sched = serving.ContinuousBatcher(eng)
    sched.submit(serving.Request([17, 3, 99], max_new_tokens=32,
                                 deadline=0.5))
    for req in sched.run():
        print(req.id, req.state, req.output_tokens)

Fleet use::

    pool, coord = serving.local_serving_fleet(2, make_engine)
    router = serving.FleetRouter(pool, slo=0.5)
    rr = router.submit([17, 3, 99], max_new_tokens=32, token="req-1")
    router.run()
    print(rr.state, rr.result)   # survives a replica kill mid-run
"""
from __future__ import annotations

from .autoscaler import AutoscalerError, FleetAutoscaler, TrafficGenerator
from .engine import DecodeEngine
from .fleet import (LocalReplica, RemoteReplica, ReplicaPool,
                    ServingHost, StaleReplicaError, local_serving_fleet,
                    serve_replica)
from .kv_cache import PagedKVCache
from .model import TinyDecoder
from .prefix import PrefixIndex
from .qos import OverQuotaError, QosPolicy, TenantSpec
from .router import FleetRouter, RoutedRequest
from .scheduler import ContinuousBatcher, Request, StaticBatcher
from .speculative import SpeculativeEngine
from . import metrics

__all__ = ["DecodeEngine", "SpeculativeEngine", "PagedKVCache",
           "PrefixIndex", "TinyDecoder",
           "ContinuousBatcher", "Request", "StaticBatcher", "metrics",
           "FleetRouter", "RoutedRequest", "ReplicaPool", "LocalReplica",
           "RemoteReplica", "ServingHost", "StaleReplicaError",
           "local_serving_fleet", "serve_replica",
           "FleetAutoscaler", "AutoscalerError", "TrafficGenerator",
           "QosPolicy", "TenantSpec", "OverQuotaError"]
