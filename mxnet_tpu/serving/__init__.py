"""Inference serving stack (ROADMAP direction 1 — the "millions of
users" front).

Everything before this PR served *training*; this package is the
production-traffic half:

- :mod:`~mxnet_tpu.serving.kv_cache` — :class:`PagedKVCache`: fixed-size
  KV pages in a preallocated device pool, per-request page tables,
  reservation-based admission, alloc/free/defrag.
- :mod:`~mxnet_tpu.serving.engine` — :class:`DecodeEngine`: ONE donated
  fixed-shape jit program per decode step (append K/V through the page
  table, ragged paged attention, greedy sample), zero per-step host
  syncs via ``engine.InflightWindow``, shape-bucketed prefill, and
  ``aot_warmup()`` so a warm replica pays zero request-path JIT.
- :mod:`~mxnet_tpu.serving.scheduler` — :class:`Request`,
  :class:`ContinuousBatcher` (admission, per-request deadlines, batch
  recomposition every step), and the :class:`StaticBatcher` A/B
  baseline.
- :mod:`~mxnet_tpu.serving.model` — the decode-model adapter protocol
  and :class:`TinyDecoder`, the pure-JAX causal LM the tests, bench,
  and examples drive.
- :mod:`~mxnet_tpu.serving.metrics` — SLO metrics
  (``mxt_serving_*``) through the PR-5 telemetry registry;
  ``tools/mxt_top.py`` renders them live.

Minimal use::

    from mxnet_tpu import serving

    model = serving.TinyDecoder(vocab=512, num_layers=2)
    eng = serving.DecodeEngine(model, slots=8)
    eng.aot_warmup()                      # or tuning.warmup()
    sched = serving.ContinuousBatcher(eng)
    sched.submit(serving.Request([17, 3, 99], max_new_tokens=32,
                                 deadline=0.5))
    for req in sched.run():
        print(req.id, req.state, req.output_tokens)
"""
from __future__ import annotations

from .engine import DecodeEngine
from .kv_cache import PagedKVCache
from .model import TinyDecoder
from .scheduler import ContinuousBatcher, Request, StaticBatcher
from . import metrics

__all__ = ["DecodeEngine", "PagedKVCache", "TinyDecoder",
           "ContinuousBatcher", "Request", "StaticBatcher", "metrics"]
