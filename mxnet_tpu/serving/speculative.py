"""Speculative decoding — more tokens per decode launch.

The plain decode engine pays one fused launch per generated token. Here
a cheap **draft model** (any adapter-protocol model, serving/model.py —
typically a truncated-layer copy of the target) proposes ``draft_k``
tokens per slot, and the target model verifies ALL of them in ONE wide
launch. Two launches per round, up to ``draft_k`` committed tokens:

- **draft chain** (1 launch): ``draft_k`` sequential single-token
  passes of the draft model, unrolled inside one jitted program over
  the draft's own (smaller) paged KV cache. The draft consumes the
  same committed prefix the target does, so its KV coverage always
  equals the target's context length — no catch-up passes, no gaps.
- **verify** (1 launch): ``draft_k`` single-token passes of the TARGET
  model unrolled inside one program, consuming ``[current_token,
  d_1..d_{k-1}]``. Each pass is literally
  :func:`~mxnet_tpu.serving.engine.one_token_pass` — the bit-identical
  op sequence sequential decode would run — so a committed token can
  never differ from the non-speculative stream: greedy token-exactness
  by construction. The accepted prefix length ``m`` (1 + matching
  draft prefix) and the commit — context lengths, current tokens — are
  computed ON DEVICE; KV rows written past ``m`` are garbage that the
  ragged length masks and the next round overwrites.

Host protocol: the engine stages one ``(slots, k+1)`` int32 row per
round — ``[m, g_1..g_k]`` per slot — into the in-flight window, so K
rounds still retire through ONE deferred transfer (host_syncs/step
unchanged; the scheduler learns every round's variable advance at
retirement via :meth:`decode_row`). Page safety without host reads:
admission reserves AND allocates ``prompt + max_new + draft_k`` tokens
of pages up front for both caches (the verify pass may overshoot the
budget by at most ``draft_k - 1`` positions; EOS-late semantics already
discard the overshoot), so no per-step page-table edit ever needs the
device-side lengths.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from . import metrics as _m
from .engine import DecodeEngine, one_token_pass
from .kv_cache import PagedKVCache

__all__ = ["SpeculativeEngine"]


class SpeculativeEngine(DecodeEngine):
    """Draft-and-verify decode over two paged KV caches."""

    def __init__(self, model, draft_model, params=None, draft_params=None,
                 draft_k=4, slots=None, cache=None, draft_cache=None,
                 prefill_buckets=(64, 256), max_context=None, seed=0):
        import jax
        import jax.numpy as jnp

        self.draft_k = int(draft_k)
        if self.draft_k < 2:
            raise MXNetError("speculative decoding needs draft_k >= 2 "
                             "(draft_k=1 is the plain engine)")
        # set BEFORE super().__init__: the base class sizes page tables
        # and admission reservations with this slack
        self._reserve_slack = self.draft_k
        self.tokens_per_step = self.draft_k
        super().__init__(model, params=params, slots=slots, cache=cache,
                         prefill_buckets=prefill_buckets,
                         max_context=max_context, seed=seed)

        self.draft_model = draft_model
        self.draft_params = draft_params if draft_params is not None \
            else draft_model.init_params(seed)
        self.dcache = draft_cache or PagedKVCache(
            draft_model.num_layers, draft_model.num_heads,
            draft_model.head_dim, num_pages=self.cache.num_pages,
            page_size=self.cache.page_size,
            quantized=self.cache.quantized)
        dS = self.dcache.page_size
        if dS != self.cache.page_size:
            raise MXNetError(
                "draft cache page size %d != target page size %d — the "
                "prefill buckets are shared, so both caches must page "
                "identically" % (dS, self.cache.page_size))
        self.dtable_width = -(-(self.max_context + self.draft_k) // dS)
        self._dpt = jnp.full((self.slots, self.dtable_width),
                             self.dcache.scratch_page, jnp.int32)
        # the draft's context length IS the target's (same committed
        # prefix, rewound together at every verify commit) — no second
        # length array exists to drift
        # ONE fused launch per speculative round: the draft chain and
        # the wide verify compose into a single donated program (the
        # verify consumes the chain's proposals as traced values — no
        # intermediate dispatch, no host hop between the halves)
        self._jit_round = jax.jit(self._round_impl,
                                  donate_argnums=(2, 3, 4))
        self._sadmit_fns = {}
        from .. import diagnostics

        diagnostics.hbm_set(
            "params", "draft_model",
            sum(l.nbytes for l in
                jax.tree_util.tree_leaves(self.draft_params)
                if hasattr(l, "nbytes")))

    # -- traced programs ---------------------------------------------------
    def _chain_impl(self, dparams, dkv, ctx, tokens, dpt, active):
        """``draft_k`` sequential draft passes in one program: returns
        the updated draft pool state and the (B, k) proposed tokens.
        ``ctx`` is read-only here (the verify program owns its donation);
        the draft writes its K/V at the same positions the target will.

        The prefix is gathered dense ONCE per layer and the chain's own
        rows land in that dense buffer as it walks (same values a
        re-gather would read — the pool pages only change where the
        buffer does); the pool itself takes one batched scatter of all
        k rows at the end. Cuts the chain's device traffic from
        k gathers + k scatters to 1 + 1 per layer."""
        import jax.numpy as jnp

        from ..ops import attention as A

        k = self.draft_k
        B = self.slots
        dm = self.draft_model
        dS = self.dcache.page_size
        scratch = self.dcache.scratch_page
        actb = active.astype(bool)
        rows = jnp.arange(B)
        pos = ctx[:, None] + jnp.arange(k, dtype=ctx.dtype)[None, :]
        page_idx = jnp.where(
            actb[:, None],
            dpt[rows[:, None], jnp.clip(pos // dS, 0,
                                        self.dtable_width - 1)],
            scratch)
        slot_idx = pos % dS
        # per-layer dense prefix views + per-layer staged window rows
        dense = [self._gather_dense_from(self.dcache, dkv, l, dpt)
                 for l in range(dm.num_layers)]
        staged_k = [[] for _ in range(dm.num_layers)]
        staged_v = [[] for _ in range(dm.num_layers)]
        t, outs = tokens, []
        for i in range(k):
            cur = ctx + i * active
            h = dm.embed(dparams, t,
                         jnp.clip(cur, 0, dm.max_len - 1))
            for l in range(dm.num_layers):
                q, kn, vn = dm.layer_qkv(dparams, l, h)   # (B, H, D)
                kd, vd = dense[l]
                kd = kd.at[rows, :, cur, :].set(kn, mode="drop")
                vd = vd.at[rows, :, cur, :].set(vn, mode="drop")
                dense[l] = (kd, vd)
                staged_k[l].append(kn)
                staged_v[l].append(vn)
                attn = A.ragged_attention_reference(
                    q, kd, vd, cur + active, sm_scale=dm.sm_scale)
                h = dm.layer_finish(dparams, l, h, attn)
            logits = dm.logits(dparams, h)
            t = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            t = jnp.where(actb, t, tokens)
            outs.append(t)
        # one batched pool scatter per layer keeps future rounds' pages
        for l in range(dm.num_layers):
            dkv = self.dcache.write_token(
                dkv, l, page_idx, slot_idx,
                jnp.stack(staged_k[l], axis=1),
                jnp.stack(staged_v[l], axis=1))
        return dkv, jnp.stack(outs, axis=1)

    @staticmethod
    def _gather_dense_from(cache, kv, layer, pt):
        """(B, H, T, D) dense K/V views of one layer's pages (dequantized
        for int8 pools) — shared by the chain and verify programs."""
        import jax.numpy as jnp

        kl, vl, ks, vs = cache.attend_views(kv, layer)
        B = pt.shape[0]
        P, S, H, D = kl.shape
        mp = pt.shape[1]
        flat = pt.reshape(-1)
        kg = kl[flat].reshape(B, mp, S, H, D)
        vg = vl[flat].reshape(B, mp, S, H, D)
        if ks is not None:
            kg = kg.astype(jnp.float32) * (
                ks[flat].reshape(B, mp, S, H) * (1.0 / 127.0))[..., None]
            vg = vg.astype(jnp.float32) * (
                vs[flat].reshape(B, mp, S, H) * (1.0 / 127.0))[..., None]
        kd = jnp.transpose(kg.reshape(B, mp * S, H, D), (0, 2, 1, 3))
        vd = jnp.transpose(vg.reshape(B, mp * S, H, D), (0, 2, 1, 3))
        return kd, vd

    def _gather_dense(self, kv, layer, pt):
        """One layer's pool pages gathered dense through the page
        table — (B, H, T, D) K and V, dequantized for int8 pools:
        exactly the gather ``ragged_paged_attention``'s XLA fallback
        performs, hoisted so the k per-position attention reads share
        it instead of re-gathering per pass."""
        return self._gather_dense_from(self.cache, kv, layer, pt)

    def _verify_impl(self, params, kv, ctx, tokens, d_toks, pt, active):
        """``draft_k`` target positions verified in one wide pass plus
        the device-side accept/commit: returns (kv, new_ctx, new_tokens,
        row) with row = (B, k+1) int32 ``[m, g_1..g_k]`` per slot.

        Layer-major like a prefill: per layer ONE batched pool scatter
        of all k new K/V rows and ONE dense gather, then k masked
        single-query attention reads (``ragged_attention_reference`` on
        the same gathered values the sequential decode path reads — the
        shapes and values per read are identical to the plain engine's,
        which is what keeps committed tokens bit-equal to its stream).
        Rows written past the accepted prefix are garbage the ragged
        masks hide and the next round overwrites."""
        import jax.numpy as jnp

        from ..ops import attention as A

        k = self.draft_k
        B = self.slots
        model = self.model
        S = self.cache.page_size
        scratch = self.cache.scratch_page
        actb = active.astype(bool)
        rows = jnp.arange(B)
        x = jnp.concatenate([tokens[:, None], d_toks[:, :k - 1]],
                            axis=1)                              # (B, k)
        pos = ctx[:, None] + jnp.arange(k)[None, :]              # (B, k)
        page_idx = jnp.where(
            actb[:, None],
            pt[rows[:, None], jnp.clip(pos // S, 0,
                                       self.table_width - 1)],
            scratch)
        slot_idx = pos % S
        h = model.embed(params, x,
                        jnp.clip(pos, 0, model.max_len - 1))     # (B,k,M)
        # per-position ragged masks, hoisted: query i sees positions
        # < ctx + i + 1 — the exact bias a sequential step at that
        # length builds (make_padding_bias), shared by every layer
        T = self.table_width * S
        biases = [A.make_padding_bias(ctx + (i + 1) * active,
                                      max_len=T, dtype="float32")
                  for i in range(k)]
        sm = float(model.sm_scale)  # sync-ok: host model hyper, not a device read
        for l in range(model.num_layers):
            q, kn, vn = model.layer_qkv(params, l, h)            # (B,k,H,D)
            kv = self.cache.write_token(kv, l, page_idx, slot_idx,
                                        kn, vn)
            kd, vd = self._gather_dense(kv, l, pt)
            attn = []
            for i in range(k):
                # single-query reference read per position — the SAME
                # op sequence (and therefore bit pattern) as the plain
                # engine's paged-attention fallback at that length
                out = A._attention_reference(
                    q[:, i][:, :, None, :], kd, vd, biases[i], False,
                    sm)
                attn.append(out[:, :, 0])
            h = model.layer_finish(params, l, h,
                                   jnp.stack(attn, axis=1))
        logits = model.logits(params, h)                         # (B,k,V)
        G = jnp.argmax(logits, axis=-1).astype(jnp.int32)        # (B,k)
        G = jnp.where(actb[:, None], G, tokens[:, None])
        # token i+1 is valid iff its input d_i matched the target's g_i
        # for EVERY i up to there: m = 1 + longest matching draft prefix
        match = (d_toks[:, :k - 1] == G[:, :k - 1])   # (B, k-1)
        # dtype pinned: cumprod would promote int32 -> int64 under x64,
        # silently retracing every warmed program at a second signature
        prefix = jnp.cumprod(match.astype(jnp.int32), axis=1,
                             dtype=jnp.int32)
        m = (1 + jnp.sum(prefix, axis=1)).astype(jnp.int32)
        m = jnp.where(actb, m, jnp.int32(0))          # (B,) in [1, k]
        newlens = ctx + m
        rows = jnp.arange(self.slots)
        new_tok = jnp.where(actb,
                            G[rows, jnp.clip(m - 1, 0, k - 1)], tokens)
        row = jnp.concatenate([m[:, None], G], axis=1).astype(jnp.int32)
        return kv, newlens, new_tok.astype(jnp.int32), row

    def _round_impl(self, params, dparams, kv, dkv, ctx, tokens, pt,
                    dpt, active):
        """One whole speculative round — draft chain then wide verify —
        as a single traced program."""
        dkv, d_toks = self._chain_impl(dparams, dkv, ctx, tokens, dpt,
                                       active)
        kv, newlens, new_tok, row = self._verify_impl(
            params, kv, ctx, tokens, d_toks, pt, active)
        return kv, dkv, newlens, new_tok, row

    # -- the decode hot path ----------------------------------------------
    def decode_step(self, meta=None):
        """One speculative round: draft chain launch + verify launch;
        the (B, k+1) accept row rides the in-flight window exactly like
        the plain engine's token row (same single deferred read per K
        rounds). Page tables were fully materialized at admission, so
        no host-side length bookkeeping runs here at all."""
        act = [s for s in range(self.slots) if self._host_active[s]]
        if not act:
            return None
        self._inflight_meta.append(meta)
        try:
            kv, dkv, ctx, tok, row = self._jit_round(
                self.params, self.draft_params, self.cache.state(),
                self.dcache.state(), self._ctx, self._tokens,
                self._pt, self._dpt, self._active_arr())
        except Exception as e:  # noqa: BLE001 — OOM gets the HBM ledger
            from .. import diagnostics

            self._inflight_meta.pop()
            diagnostics.reraise_if_oom(e, "serving_spec_decode")
            raise
        self.dcache.swap(dkv)
        self.cache.swap(kv)
        self._ctx, self._tokens = ctx, tok
        _m.spec_proposed_total().inc((self.draft_k - 1) * len(act))
        _m.decode_batch_occupancy().observe(len(act))
        return self.window.push(row, value=row)

    def decode_row(self, row, slot):
        """The accepted prefix one retired round carries for ``slot``:
        ``row[slot] = [m, g_1..g_k]`` — m committed tokens. Feeds the
        acceptance-rate and throughput counters (deferred accounting:
        this runs inside the window's one read per K rounds)."""
        m = int(row[slot, 0])
        toks = [int(t) for t in row[slot, 1:1 + m]]
        if m > 0:
            _m.tokens_total().inc(m)
            _m.spec_accepted_total().inc(m - 1)
        return toks

    # -- admission ---------------------------------------------------------
    def can_admit(self, total_tokens, prompt=None):
        # prefix reuse doesn't compose with speculative decode (the
        # verify overshoot writes past the committed budget), so the
        # prompt is ignored here — both pools gate on the plain bill
        del prompt
        padded = total_tokens + self._reserve_slack
        return (self.cache.can_reserve(padded)
                and self.dcache.can_reserve(padded))

    def _post_reserve(self, seq_id, total):
        """Materialize the full worst-case allocation in BOTH caches
        the moment the target reservation lands: decode rounds then
        never touch page bookkeeping, and the page-table rows written
        at admission are complete (no lazy growth, no host-side length
        tracking — the device owns the lengths)."""
        padded = total + self._reserve_slack
        if not self.dcache.reserve(seq_id, padded):
            self.cache.free(seq_id)
            raise MXNetError("draft KV pool too busy for sequence %r "
                             "(check engine.can_admit before admitting)"
                             % (seq_id,))
        # the worst-case pages were promised at reservation: cannot fail
        self.cache.alloc_for(seq_id, padded)
        self.dcache.alloc_for(seq_id, padded)

    def _dprefill_impl(self, dparams, tokens, valid, *, bucket):
        import jax.numpy as jnp

        dm = self.draft_model
        dS = self.dcache.page_size
        nbp = bucket // dS
        ks, vs, _ = dm.prefill(dparams, tokens, valid)
        kr = jnp.transpose(ks[:, 0], (0, 2, 1, 3)).reshape(
            dm.num_layers, nbp, dS, dm.num_heads, dm.head_dim)
        vr = jnp.transpose(vs[:, 0], (0, 2, 1, 3)).reshape(
            dm.num_layers, nbp, dS, dm.num_heads, dm.head_dim)
        return kr, vr

    def _sadmit_impl(self, params, dparams, kv, dkv, pt, dpt, tokens,
                     ctx, padded, valid, ids, dids, row, drow, slot, t,
                     *, bucket):
        """One fused dispatch for the WHOLE speculative admission:
        target prefill + page write + slot commit (the base program)
        and the draft prefill + page write + draft page-table row."""
        kv, pt, tokens, ctx, tok0 = self._admit_impl(
            params, kv, pt, tokens, ctx, padded, valid, ids, row,
            slot, t, bucket=bucket)
        dkr, dvr = self._dprefill_impl(dparams, padded, valid,
                                       bucket=bucket)
        dkv = self.dcache.write_pages(dkv, dkr, dvr, dids)
        return kv, dkv, pt, dpt.at[slot].set(drow), tokens, ctx, tok0

    def _sadmit_fn(self, bucket):
        import functools

        import jax

        fn = self._sadmit_fns.get(bucket)
        if fn is None:
            fn = self._sadmit_fns[bucket] = jax.jit(
                functools.partial(self._sadmit_impl, bucket=bucket),
                donate_argnums=(2, 3, 4, 5, 7))
        return fn

    def admit(self, slot, seq_id, prompt_tokens, max_new_tokens):
        """Both halves of a speculative admission in ONE dispatch: the
        _post_reserve hook reserved + allocated both caches up front,
        then the fused program prefills target AND draft, scatters both
        prompt K/V page sets, and commits the slot state."""
        import jax.numpy as jnp

        from ..ndarray.pending import PendingValue

        p = self._admit_prep(slot, seq_id, prompt_tokens, max_new_tokens)
        dS = self.dcache.page_size
        dnbp = p["bucket"] // dS
        dpages = self.dcache.pages_of(seq_id)
        dids = np.full((dnbp,), self.dcache.scratch_page, np.int32)
        n = min(len(dpages), dnbp)
        dids[:n] = dpages[:n]  # bucket tail pages scatter to scratch
        drow = self.dcache.page_table_row(seq_id, self.dtable_width)
        try:
            (kv, dkv, self._pt, self._dpt, self._tokens, self._ctx,
             tok0) = self._sadmit_fn(p["bucket"])(
                self.params, self.draft_params, self.cache.state(),
                self.dcache.state(), self._pt, self._dpt, self._tokens,
                self._ctx, jnp.asarray(p["padded"]),
                jnp.asarray(np.array([p["T"]], np.int32)),
                jnp.asarray(p["ids"]), jnp.asarray(dids),
                jnp.asarray(p["row"]), jnp.asarray(drow),
                np.int32(slot), np.int32(p["T"]))
        except Exception as e:  # noqa: BLE001 — OOM gets the HBM ledger
            from .. import diagnostics

            self.cache.free(seq_id)
            self.dcache.free(seq_id)
            diagnostics.reraise_if_oom(e, "serving_prefill")
            raise
        self.cache.swap(kv)
        self.dcache.swap(dkv)
        self._seq_of_slot[slot] = seq_id
        self._host_active[slot] = True
        self._host_len[slot] = p["T"]
        _m.tokens_total().inc()  # the prefill-sampled first token
        return PendingValue(tok0)

    # -- recomposition -----------------------------------------------------
    def release(self, slot):
        """Retire a slot in BOTH caches (stale page-table rows stay —
        masked for inactive slots, overwritten at the next admission)."""
        seq = self._seq_of_slot.get(slot)
        super().release(slot)
        if seq is not None:
            self.dcache.free(seq)

    def defrag(self):
        """Compact both pools; re-emit every live slot's rows for both
        page tables."""
        import jax.numpy as jnp

        moved = super().defrag()
        dmoved = self.dcache.defrag()
        if dmoved:
            for s, seq in self._seq_of_slot.items():
                self._dpt = self._dpt.at[s].set(jnp.asarray(
                    self.dcache.page_table_row(seq, self.dtable_width)))
        return moved + dmoved

    # -- AOT warm-start ----------------------------------------------------
    def aot_warmup(self):
        """Lower-and-compile every request-path program: the draft
        chain, the wide verify, and the fused two-model admission per
        prefill bucket. (The plain single-token step is not compiled —
        this engine never dispatches it.)"""
        import jax
        import jax.numpy as jnp

        def sds(a):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        i32 = jnp.int32
        pstruct = jax.tree_util.tree_map(sds, self.params)
        dstruct = jax.tree_util.tree_map(sds, self.draft_params)
        kv_sds = tuple(sds(a) for a in self.cache.state())
        dkv_sds = tuple(sds(a) for a in self.dcache.state())
        act = jax.ShapeDtypeStruct((self.slots,), i32)
        n = 0
        self._jit_round.lower(
            pstruct, dstruct, kv_sds, dkv_sds, sds(self._ctx),
            sds(self._tokens), sds(self._pt), sds(self._dpt),
            act).compile()
        n += 1
        S, dS = self.cache.page_size, self.dcache.page_size
        for bucket in list(self._buckets):
            self._sadmit_fn(bucket).lower(
                pstruct, dstruct, kv_sds, dkv_sds, sds(self._pt),
                sds(self._dpt), sds(self._tokens), sds(self._ctx),
                jax.ShapeDtypeStruct((1, bucket), i32),
                jax.ShapeDtypeStruct((1,), i32),
                jax.ShapeDtypeStruct((bucket // S,), i32),
                jax.ShapeDtypeStruct((bucket // dS,), i32),
                jax.ShapeDtypeStruct((self.table_width,), i32),
                jax.ShapeDtypeStruct((self.dtable_width,), i32),
                jax.ShapeDtypeStruct((), i32),
                jax.ShapeDtypeStruct((), i32)).compile()
            n += 1
        return n
