"""Paged KV-cache manager — fixed-size KV pages in a preallocated
device pool (the serving half of ROADMAP direction 1; the design is the
paged-attention memory model of PAPERS.md arXiv 2604.15464).

Why pages: a decode batch holds sequences of wildly different lengths,
and a dense (B, H, Tmax, D) cache pays Tmax for every slot. Here the
pool is ``(layers, pages + 1, page_size, heads, head_dim)`` per K and V,
sequences own *page lists*, and the ragged paged attention kernel
(ops/attention.py) streams exactly the pages a sequence uses. Slot
reuse, mixed lengths, and request churn cost page-table edits, never
pool reallocation or recompilation.

Pool arrays are functional jax values: the decode step *donates* them
through the jitted program (append-in-place at the XLA level), and the
cache swaps in each step's output arrays. Host-side state is pure
bookkeeping — free list, per-sequence page lists, reservations — and
never reads the device (this module is on the check_host_syncs.py scan
list).

Admission control is worst-case reservation: :meth:`reserve` promises
``ceil((prompt + max_new) / page_size)`` pages up front, so a running
decode can never hit pool exhaustion mid-flight; pages are *allocated*
lazily as the sequence actually crosses page boundaries, and
:meth:`defrag` compacts live pages to the low end of the pool (pool
shrink / DMA-locality maintenance).

The extra page at index ``num_pages`` is the **scratch page**: masked
writes of inactive batch slots and padded page-table entries route
there, keeping the decode program's shapes fixed without conditional
writes.

Shared prefixes (serving/prefix.py): pages carry **refcounts** — a new
sequence admitted against a cached prefix lists the *same* pool pages
in its page table (:meth:`reserve` with ``shared=``), each reference
bumping the page's count; :meth:`free` decrements and only a page's
LAST reference returns it to the free list. The prefix index itself
holds references too (:meth:`retain_pages`/:meth:`release_pages`), so
an indexed prefix survives its originating sequence. A write landing
in a shared page goes copy-on-write: :meth:`cow_page` swaps a fresh
page into the sequence's list (host bookkeeping; the device-side page
copy runs inside the caller's fused admission program), and ``cow``
debt is part of the reservation promise so a fully-shared admission
cannot overcommit the pool. :meth:`defrag` compacts by refcount — any
referenced page survives, including index-pinned pages owned by no
sequence — and notifies registered movers (:meth:`add_mover`) with the
id remapping.

Quantized pages (``quantized=True``): the K/V pools store symmetric
signed int8 with a per-(position, head) float32 amax alongside —
``scale(q) = 127 / amax``, the ops/quantization.py triple convention
with the range carried as one scalar per row instead of a (min, max)
pair. Page bytes drop ~4x (int8 payload + scales worth 4/head_dim of
it), so the same byte budget holds ~4x the pages / resident sequences;
the decode step quantizes each appended K/V row on device and the
attention read dequantizes after the page gather
(ops/attention.ragged_paged_attention's XLA fallback — the Pallas
kernel path declines quantized pools). All pool arrays — payload and
scales — travel the same donated-through-the-program route; the
functional ``state()`` tuple is what the engine threads through its
jitted programs.
"""
from __future__ import annotations

import threading

import numpy as np

from ..base import MXNetError
from . import metrics as _m

__all__ = ["PagedKVCache"]


def _config():
    from .. import config

    return config


class PagedKVCache:
    """One serving replica's KV page pool + page-table bookkeeping."""

    def __init__(self, num_layers, num_heads, head_dim, num_pages=None,
                 page_size=None, dtype="float32", quantized=False):
        import jax.numpy as jnp

        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.page_size = int(page_size or _config().get("MXT_PAGE_SIZE"))
        if self.page_size < 8 or self.page_size % 8:
            raise MXNetError("MXT_PAGE_SIZE must be a positive multiple "
                             "of 8 (TPU sublane), got %d" % self.page_size)
        self.num_pages = int(num_pages
                             or _config().get("MXT_SERVING_PAGES"))
        if self.num_pages < 1:
            raise MXNetError("a KV cache needs at least one page")
        self.quantized = bool(quantized)
        self.dtype = jnp.dtype("int8" if self.quantized else dtype)
        shape = (self.num_layers, self.num_pages + 1, self.page_size,
                 self.num_heads, self.head_dim)
        self.k_pages = jnp.zeros(shape, self.dtype)
        self.v_pages = jnp.zeros(shape, self.dtype)
        self.k_scales = self.v_scales = None
        if self.quantized:
            sshape = shape[:-1]  # one amax per (layer, page, pos, head)
            self.k_scales = jnp.zeros(sshape, jnp.float32)
            self.v_scales = jnp.zeros(sshape, jnp.float32)

        self._lock = threading.Lock()
        self._free = list(range(self.num_pages - 1, -1, -1))  # pop() = 0
        self._pages = {}     # seq_id -> [page ids, in sequence order]
        self._quota = {}     # seq_id -> reserved page count (total)
        self._refs = {}      # page id -> reference count (>= 1)
        self._cow = {}       # seq_id -> outstanding copy-on-write debt
        self._movers = []    # defrag listeners: cb({old page: new page})
        _m.kv_pages_total().set(self.num_pages)
        # diagnostics HBM ledger: the whole preallocated K+V pool
        # (scratch page + scale planes included) — .nbytes is shape
        # metadata, no read
        from .. import diagnostics

        diagnostics.hbm_set("kv_cache", "pool",
                            sum(a.nbytes for a in self.state()))
        self._publish()

    @classmethod
    def pages_for_budget(cls, nbytes, num_layers, num_heads, head_dim,
                         page_size=None, dtype="float32", quantized=False):
        """How many pool pages a byte budget buys at this geometry —
        the capacity half of the kv_quant A/B: the quantized pool packs
        ~4x the pages (so ~4x the resident sequences) into the same
        budget. Scratch page and scale planes are charged too."""
        import numpy as np

        page_size = int(page_size or _config().get("MXT_PAGE_SIZE"))
        per_pos = num_heads * head_dim * (
            1 if quantized else np.dtype(dtype).itemsize)
        if quantized:
            per_pos += num_heads * 4  # the f32 amax plane
        page_bytes = 2 * num_layers * page_size * per_pos  # K and V
        return max(0, int(nbytes) // page_bytes - 1)  # -1: scratch page

    @property
    def page_bytes(self):
        """Device bytes one pool page costs (K + V + scales, all
        layers) — shape metadata only."""
        total = sum(a.nbytes for a in self.state())
        return total // (self.num_pages + 1)

    # -- helpers ----------------------------------------------------------
    @property
    def scratch_page(self):
        """Pool index of the masked-write scratch page."""
        return self.num_pages

    def pages_needed(self, ntokens):
        return -(-int(ntokens) // self.page_size)

    def _publish(self):
        in_use = self.num_pages - len(self._free)
        reserved = (sum(self._quota.values())
                    - sum(len(p) for p in self._pages.values())
                    + sum(self._cow.values()))
        _m.kv_pages_in_use().set(in_use)
        _m.kv_pages_reserved().set(max(0, reserved))
        _m.shared_pages().set(
            sum(1 for c in self._refs.values() if c > 1))
        if self.quantized:
            # quantized-page occupancy: its own gauge so mxt_top can
            # show how much of the serving load runs on int8 pages
            _m.kv_quant_pages_in_use().set(in_use)

    # -- reservation + allocation ----------------------------------------
    def available(self):
        """Pages free AND unpromised — what admission may still reserve.
        Outstanding copy-on-write debts count as promises: a fully
        shared admission still owes the pool its divergence page."""
        with self._lock:
            unallocated = (sum(self._quota.values())
                           - sum(len(p) for p in self._pages.values())
                           + sum(self._cow.values()))
            return len(self._free) - unallocated

    def can_reserve(self, ntokens, shared=0, cow=0):
        """Would :meth:`reserve` succeed right now? ``shared`` pages
        come refcounted from the prefix index (no free-list draw);
        ``cow`` is the extra copy-on-write page debt."""
        need = self.pages_needed(ntokens) - int(shared) + int(cow)
        return need <= self.available()

    def reserve(self, seq_id, ntokens, shared=(), cow=0):
        """Promise ``ceil(ntokens / page_size)`` pages to ``seq_id``
        (its lifetime worst case). False = pool too busy — the request
        stays queued. A sequence reserves once.

        ``shared`` seeds the sequence's page list with already-resident
        prefix pages (each gains a reference — they are NOT drawn from
        the free list, which is the whole capacity win); ``cow`` pages
        of copy-on-write debt join the promise so the later
        :meth:`cow_page` draw cannot fail."""
        npages = self.pages_needed(ntokens)
        if npages > self.num_pages:
            raise MXNetError(
                "request needs %d KV pages but the pool only has %d — "
                "raise MXT_SERVING_PAGES or shorten prompt+max_new"
                % (npages, self.num_pages))
        shared = list(shared)
        if self.available() < npages - len(shared) + int(cow):
            return False
        with self._lock:
            if seq_id in self._quota:
                raise MXNetError("sequence %r already holds a "
                                 "reservation" % (seq_id,))
            for p in shared:
                if self._refs.get(p, 0) < 1:
                    raise MXNetError(
                        "shared page %d is not resident (stale prefix "
                        "index entry?)" % (p,))
            self._quota[seq_id] = npages
            self._pages[seq_id] = shared
            for p in shared:
                self._refs[p] += 1
            if cow:
                self._cow[seq_id] = int(cow)
        self._publish()
        return True

    def alloc_page(self, seq_id):
        """Materialize the next page of a reserved sequence; returns the
        pool page id. Reservation-bounded, so this cannot fail mid-decode
        (the admission check already paid for it)."""
        with self._lock:
            if seq_id not in self._quota:
                raise MXNetError("sequence %r has no reservation"
                                 % (seq_id,))
            pages = self._pages[seq_id]
            if len(pages) >= self._quota[seq_id]:
                raise MXNetError(
                    "sequence %r exceeded its %d-page reservation"
                    % (seq_id, self._quota[seq_id]))
            page = self._free.pop()
            self._refs[page] = 1
            pages.append(page)
        self._publish()
        return page

    def alloc_for(self, seq_id, ntokens):
        """Allocate pages until ``ntokens`` positions are covered;
        returns the new page ids (possibly empty)."""
        new = []
        while len(self.pages_of(seq_id)) < self.pages_needed(ntokens):
            new.append(self.alloc_page(seq_id))
        return new

    def cow_page(self, seq_id, idx):
        """Copy-on-write: the sequence is about to WRITE into its
        ``idx``-th page while other references share it. Swap a fresh
        page into the list (host bookkeeping only — the caller's fused
        admission program performs the device-side page copy before its
        scatter) and retire one page of COW debt. Returns
        ``(src_page, dst_page)`` for that device copy."""
        with self._lock:
            pages = self._pages[seq_id]
            src = pages[idx]
            dst = self._free.pop()
            self._refs[dst] = 1
            pages[idx] = dst
            self._refs[src] -= 1
            if self._refs[src] == 0:  # last ref raced away: still correct
                del self._refs[src]
                self._free.append(src)
            debt = self._cow.get(seq_id, 0) - 1
            if debt > 0:
                self._cow[seq_id] = debt
            else:
                self._cow.pop(seq_id, None)
        _m.cow_copies_total().inc()
        self._publish()
        return src, dst

    def refcount(self, page):
        with self._lock:
            return self._refs.get(page, 0)

    def retain_pages(self, pages):
        """Take an ownership reference on resident pages (the prefix
        index pinning a cached prefix past its originating sequence)."""
        with self._lock:
            for p in pages:
                if self._refs.get(p, 0) < 1:
                    raise MXNetError("cannot retain non-resident page %d"
                                     % (p,))
            for p in pages:
                self._refs[p] += 1
        self._publish()

    def release_pages(self, pages):
        """Drop references taken with :meth:`retain_pages`; pages whose
        last reference this was return to the free list."""
        freed = []
        with self._lock:
            for p in pages:
                self._refs[p] -= 1
                if self._refs[p] == 0:
                    del self._refs[p]
                    freed.append(p)
            self._free.extend(reversed(freed))
        self._publish()
        return len(freed)

    def free(self, seq_id):
        """Release a sequence: each of its pages drops one reference
        and only last references return to the free list (shared prefix
        pages survive for the index / sibling sequences); the
        reservation dissolves. In-flight decode steps that still read
        the pages are safe — they consumed earlier pool *values*, and a
        later prefill writing a recycled page produces a new value the
        old steps never see (XLA dataflow, not aliasing)."""
        with self._lock:
            pages = self._pages.pop(seq_id, [])
            self._quota.pop(seq_id, None)
            self._cow.pop(seq_id, None)
            released = []
            for p in pages:
                self._refs[p] -= 1
                if self._refs[p] == 0:
                    del self._refs[p]
                    released.append(p)
            self._free.extend(reversed(released))
        self._publish()
        return len(pages)

    def pages_of(self, seq_id):
        with self._lock:
            return list(self._pages.get(seq_id, ()))

    def sequences(self):
        with self._lock:
            return sorted(self._pages)

    def pages_in_use(self):
        with self._lock:
            return self.num_pages - len(self._free)

    # -- device plumbing --------------------------------------------------
    def state(self):
        """The pool's functional device state as one tuple — what the
        engine donates through its jitted programs. ``(k, v)`` for f32
        pools, ``(k, v, k_scales, v_scales)`` for quantized ones."""
        if self.quantized:
            return (self.k_pages, self.v_pages,
                    self.k_scales, self.v_scales)
        return (self.k_pages, self.v_pages)

    def swap(self, *state):
        """Adopt the pool arrays a donated decode/prefill program
        returned (the old ones were its inputs and are now invalid).
        Accepts the :meth:`state` tuple, splatted or as one argument."""
        if len(state) == 1 and isinstance(state[0], (tuple, list)):
            state = tuple(state[0])
        self.k_pages, self.v_pages = state[0], state[1]
        if self.quantized:
            self.k_scales, self.v_scales = state[2], state[3]

    @staticmethod
    def _quantize(x):
        """Symmetric int8 per-(…, head) row quantization: amax over the
        head_dim axis, q = round(x * 127/amax). Pure device math — runs
        inside the jitted decode/prefill programs."""
        import jax.numpy as jnp

        amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
        q = jnp.clip(jnp.round(x.astype(jnp.float32)
                               * (127.0 / jnp.maximum(amax, 1e-30))
                               [..., None]), -127, 127).astype(jnp.int8)
        return q, amax

    def write_token(self, state, layer, page_idx, slot_idx, kn, vn):
        """Functionally append one token's K/V rows — ``kn``/``vn`` are
        (B, H, D) float — into layer ``layer`` at (page, in-page slot)
        per batch row; returns the new state tuple. Quantized pools
        quantize on device and store the amax plane alongside."""
        if self.quantized:
            kq, ka = self._quantize(kn)
            vq, va = self._quantize(vn)
            kp = state[0].at[layer, page_idx, slot_idx].set(kq)
            vp = state[1].at[layer, page_idx, slot_idx].set(vq)
            ks = state[2].at[layer, page_idx, slot_idx].set(ka)
            vs = state[3].at[layer, page_idx, slot_idx].set(va)
            return (kp, vp, ks, vs)
        kp = state[0].at[layer, page_idx, slot_idx].set(
            kn.astype(state[0].dtype))
        vp = state[1].at[layer, page_idx, slot_idx].set(
            vn.astype(state[1].dtype))
        return (kp, vp)

    def attend_views(self, state, layer):
        """One layer's pool views for the attention read:
        ``(k, v, k_scales, v_scales)`` with None scales for f32 pools —
        exactly the argument shape ragged_paged_attention takes."""
        if self.quantized:
            return (state[0][layer], state[1][layer],
                    state[2][layer], state[3][layer])
        return state[0][layer], state[1][layer], None, None

    def write_pages(self, state, k_rows, v_rows, page_ids):
        """Functionally install whole prefill pages: ``k_rows``/
        ``v_rows`` are float ``(L, n, S, H, D)``, ``page_ids`` (n,)
        pool indices (scratch-padded tails welcome). Quantizes first on
        quantized pools; returns the new state tuple."""
        if self.quantized:
            kq, ka = self._quantize(k_rows)
            vq, va = self._quantize(v_rows)
            return (state[0].at[:, page_ids].set(kq),
                    state[1].at[:, page_ids].set(vq),
                    state[2].at[:, page_ids].set(ka),
                    state[3].at[:, page_ids].set(va))
        return (state[0].at[:, page_ids].set(k_rows.astype(state[0].dtype)),
                state[1].at[:, page_ids].set(v_rows.astype(state[1].dtype)))

    def page_table_row(self, seq_id, width):
        """(width,) int32 page-table row for a batch slot: the
        sequence's pages in order, scratch-padded (a padded slot must
        stay a *valid* pool index — the kernel reads it and masks)."""
        pages = self.pages_of(seq_id)
        if len(pages) > width:
            raise MXNetError("sequence %r uses %d pages > table width %d"
                             % (seq_id, len(pages), width))
        row = np.full((width,), self.scratch_page, np.int32)
        row[:len(pages)] = pages
        return row

    # -- defrag -----------------------------------------------------------
    def add_mover(self, cb):
        """Register a defrag listener: ``cb({old_page: new_page})``
        fires after every compaction that moved pages (the prefix index
        remaps its cached page lists through it)."""
        self._movers.append(cb)

    def defrag(self):
        """Compact live pages to the low end of the pool: after churn
        the free list is scattered and long-lived sequences pin high
        page ids; compaction restores contiguity (DMA locality, and the
        precondition for ever shrinking the pool). Liveness is the
        REFCOUNT map, not the sequence lists — an index-pinned prefix
        page owned by no sequence moves with everything else, never
        into the free list. One gather/scatter pair on device per pool;
        page tables on the NEXT decode step pick up the moved ids
        (callers must re-emit device page-table rows for live slots —
        serving.DecodeEngine.defrag does), and registered movers
        (:meth:`add_mover`) get the id remapping.

        Returns the number of pages moved."""
        with self._lock:
            used = sorted(self._refs)
            mapping = {old: new for new, old in enumerate(used)
                       if old != new}
            if not mapping:
                return 0
            src = np.array(sorted(mapping), np.int32)
            dst = np.array([mapping[s] for s in sorted(mapping)], np.int32)
            self._pages = {
                seq: [mapping.get(p, p) for p in pages]
                for seq, pages in self._pages.items()}
            self._refs = {mapping.get(p, p): c
                          for p, c in self._refs.items()}
            self._free = list(range(self.num_pages - 1, len(used) - 1, -1))
        # functional scatter: RHS gathers from the OLD array, so
        # overlapping src/dst ranges cannot clobber each other
        self.k_pages = self.k_pages.at[:, dst].set(self.k_pages[:, src])
        self.v_pages = self.v_pages.at[:, dst].set(self.v_pages[:, src])
        if self.quantized:
            self.k_scales = self.k_scales.at[:, dst].set(
                self.k_scales[:, src])
            self.v_scales = self.v_scales.at[:, dst].set(
                self.v_scales[:, src])
        for cb in self._movers:
            cb(dict(mapping))
        self._publish()
        return len(src)
