"""Shared-prefix index — content-addressed reuse of resident KV pages.

At millions-of-users scale most prompts share prefixes (system prompts,
few-shot preambles), and the paged KV cache already stores those
prefixes as fixed-size pages: the only missing piece is a map from
*prompt content* to *resident pages*. This module is that map.

Keying: a blake2b **chain** over page-size-aligned token blocks — the
same chunk-fingerprint discipline as the data plane's manifest. Block
``j``'s digest hashes ``digest(j-1) || tokens[j*S:(j+1)*S]``, so a
digest names the ENTIRE prefix up to that block, not just the block:
two prompts share an entry iff they are token-identical up to that
page boundary. Every admitted prompt registers ALL its full-block
chain digests, so a later prompt matching any page-aligned prefix hits
at the longest shared boundary.

Ownership: each entry holds an index-side REFERENCE on its pages
(kv_cache.retain_pages), so a cached prefix survives the sequence that
created it; eviction is LRU under pool pressure (:meth:`trim`), and
defrag remaps entries through the cache's mover callback. Hash math
runs host-side at admission — control plane, never inside the decode
loop (this module is on the check_host_syncs.py scan list; its
sanctioned numpy call hashes host token lists).
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict

import numpy as np

from . import metrics as _m

__all__ = ["PrefixIndex"]

_DIGEST_BYTES = 16


class PrefixIndex:
    """LRU map: chain digest of a page-aligned prompt prefix -> the
    resident pool pages holding its KV state."""

    def __init__(self, cache, capacity=1024):
        self.cache = cache
        self.page_size = cache.page_size
        self.capacity = int(capacity)
        # digest -> (pages tuple, ntokens covered); insertion order = LRU
        self._entries = OrderedDict()
        cache.add_mover(self._remap)

    # -- keying -----------------------------------------------------------
    def chain(self, prompt):
        """The digest chain of ``prompt``'s full page-size blocks:
        ``chain[j]`` names tokens ``[0, (j+1)*S)``. Host-side hashing —
        admission control plane."""
        S = self.page_size
        out = []
        h = b""
        for j in range(len(prompt) // S):
            block = np.asarray(  # sync-ok: host token list hashing
                prompt[j * S:(j + 1) * S], np.int32)
            h = hashlib.blake2b(h + block.tobytes(),
                                digest_size=_DIGEST_BYTES).digest()
            out.append(h)
        return out

    # -- lookup + registration -------------------------------------------
    def lookup(self, prompt):
        """Longest cached page-aligned prefix of ``prompt``:
        ``(pages, covered_tokens, chain)`` — empty/0 on a miss. The hit
        entry (and every shorter chain entry) moves to MRU. The caller
        must take its own references (kv_cache.reserve ``shared=``)
        before the pages are safe from :meth:`trim`."""
        chain = self.chain(prompt)
        for j in range(len(chain) - 1, -1, -1):
            entry = self._entries.get(chain[j])
            if entry is not None:
                self._entries.move_to_end(chain[j])
                pages, ntok = entry
                return list(pages), ntok, chain
        return [], 0, chain

    def register(self, prompt, pages, chain=None):
        """Index an admitted prompt: every full-block chain digest maps
        to its page prefix, each entry retaining its pages so they
        outlive the sequence. Known digests just refresh to MRU."""
        chain = self.chain(prompt) if chain is None else chain
        added = 0
        for j, digest in enumerate(chain):
            if digest in self._entries:
                self._entries.move_to_end(digest)
                continue
            prefix = tuple(pages[:j + 1])
            if len(prefix) < j + 1:
                break  # caller shipped fewer pages than blocks
            self.cache.retain_pages(prefix)
            self._entries[digest] = (prefix, (j + 1) * self.page_size)
            added += 1
        while len(self._entries) > self.capacity:
            self._evict_lru()
        return added

    # -- eviction ---------------------------------------------------------
    def _evict_lru(self, keep=()):
        for digest in self._entries:
            if digest not in keep:
                pages, _ = self._entries.pop(digest)
                self.cache.release_pages(pages)
                return True
        return False

    def trim(self, need_pages, keep=()):
        """Evict LRU entries (skipping ``keep`` digests — the hit an
        admission is about to consume) until the cache can hand out
        ``need_pages`` more pages, or the index runs dry. Returns True
        when the pool can now satisfy the request."""
        keep = frozenset(keep)
        while self.cache.available() < need_pages:
            if not self._evict_lru(keep):
                return self.cache.available() >= need_pages
        return True

    def clear(self):
        """Drop every entry (and its page references)."""
        while self._entries:
            self._evict_lru()

    # -- defrag -----------------------------------------------------------
    def _remap(self, mapping):
        """kv_cache defrag mover: rewrite cached page ids in place."""
        self._entries = OrderedDict(
            (d, (tuple(mapping.get(p, p) for p in pages), ntok))
            for d, (pages, ntok) in self._entries.items())

    # -- introspection ----------------------------------------------------
    def __len__(self):
        return len(self._entries)

    def entries(self):
        """[(covered_tokens, pages tuple)] in LRU->MRU order."""
        return [(ntok, pages)
                for pages, ntok in self._entries.values()]

    def hit(self):
        _m.prefix_hits_total().inc()

    def miss(self):
        _m.prefix_misses_total().inc()
