"""SLO-aware fleet router — the dispatch half of the serving front door.

One :class:`FleetRouter` fronts a :class:`~mxnet_tpu.serving.fleet.
ReplicaPool`: it owns the request queue, picks replicas by load
(queue-depth + active slots over capacity — the gauges replicas already
export), and wires the three robustness behaviors end to end:

- **Failover.** A request whose replica is reaped mid-flight (the
  membership death listener, or a transport failure observed at
  dispatch) is transparently re-enqueued onto a survivor. Every routed
  request carries an **idempotency token**: a replay of an
  already-completed token returns the recorded result — it never
  re-decodes. Dispatch retries ride ``resilience.kv_retry``'s typed
  backoff/deadline machinery, so a fleet with no survivors surfaces as
  a clean :class:`~mxnet_tpu.resilience.KVStoreError`, never a hang.

- **Hedged dispatch.** A request with no result past its SLO-derived
  hedge delay (half its deadline, or the router's ``slo``, or
  ``MXT_FLEET_HEDGE_DELAY``) is speculatively duplicated onto a second
  replica. First completion wins and is committed once; the loser is
  cancelled through the replica scheduler's eviction path. The hedge
  budget (``MXT_FLEET_HEDGE_BUDGET``, default fleet-capacity/4) bounds
  concurrent hedges so a brownout cannot double the fleet's load.

- **Fencing.** Completions are accepted through one gate: a reply from
  a fenced replica (reaped zombie, killed, replaced) raises the typed
  :class:`~mxnet_tpu.serving.fleet.StaleReplicaError` and is never
  committed — the request's failover copy is the only writer.

- **Disaggregated prefill.** When the pool carries both ``prefill``-
  and ``decode``-role replicas, a long prompt (>=
  ``MXT_FLEET_PREFILL_THRESHOLD`` tokens) dispatches as a handoff:
  prefill on the prefill tier, ship the finished KV pages over the
  transport (``srv_ship_pages``), adopt them into a decode replica
  (``srv_adopt_pages``) — the request enters decode with zero prefill
  work on the decode tier. The chain rides ``kv_retry``: a prefill
  replica that dies mid-ship is marked dead and the retry re-ships
  from a survivor (idempotent by copy id); an exhausted prefill tier
  falls back to ordinary local-prefill dispatch, so disaggregation
  never loses a request. Short prompts route straight to the decode
  tier. ``ship``/``adopt`` spans stamp the handoff on the router's
  trace track.

- **Multi-tenant QoS.** With a :class:`~mxnet_tpu.serving.qos.
  QosPolicy` attached, submissions carry a tenant id + priority class:
  admission charges the tenant's outstanding quota (typed
  :class:`~mxnet_tpu.serving.qos.OverQuotaError` refusal when
  exhausted — never a silent drop; refunded at the finish gate),
  dispatch picks the best (lowest) priority class first (FIFO within a
  class), and a replica scheduler that PREEMPTS a bulk request to seat
  an interactive one reports the copy back as ``preempted`` — a
  non-terminal outcome the router re-enqueues at the BACK of the queue
  (it yields) through the same idempotent machinery as failover, so
  preempted bulk is late, never lost.

Host/device split: the router is PURE host bookkeeping over host
scalars (queue lengths, wall-clock stamps, token lists already
materialized by the replicas' deferred windows). It performs zero
device reads — tools/check_host_syncs.py lint-enforces that.

Telemetry: ``mxt_fleet_replicas{state}``, per-replica
``mxt_fleet_{dispatch,hedges,failovers,stale_replies}_total``,
``mxt_fleet_requests_total{outcome}``, ``mxt_fleet_replays_total``,
and the ``mxt_fleet_request_latency_seconds`` histogram — all rendered
by ``tools/mxt_top.py``'s fleet section.
"""
from __future__ import annotations

import collections
import itertools
import time

from .. import telemetry
from ..base import MXNetError
from ..resilience import KVStoreError
from . import metrics as _m
from .fleet import DEAD, DRAINING, ROUTABLE, StaleReplicaError

__all__ = ["RoutedRequest", "FleetRouter"]

_tok_ids = itertools.count()

_TRACK = "router"  # the router's row in the distributed trace timeline


class RoutedRequest:
    """One fleet-level request: prompt + budget + SLO, the idempotency
    token, and the dispatch/hedge/failover record the router fills in.
    ``result`` is the committed token list (exactly one commit ever
    happens per token — ``commits`` asserts it)."""

    __slots__ = ("token", "prompt", "max_new_tokens", "deadline",
                 "eos_id", "state", "result", "committed_by", "commits",
                 "copies", "dispatches", "hedges", "failovers",
                 "hedge_delay", "t_submit", "t_dispatch", "t_finish",
                 "trace_id", "_ncopy", "tenant", "priority",
                 "preemptions")

    def __init__(self, prompt, max_new_tokens=16, deadline=None,
                 eos_id=None, token=None, tenant=None, priority=None):
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.deadline = None if deadline is None else float(deadline)  # sync-ok: host scalar
        self.eos_id = eos_id
        self.token = token if token is not None \
            else "fr-%d" % next(_tok_ids)
        self.state = "queued"  # queued|dispatched|completed|evicted|rejected
        self.tenant = None if tenant is None else str(tenant)
        self.priority = 0 if priority is None else int(priority)
        self.preemptions = 0
        self.result = None
        self.committed_by = None
        self.commits = 0
        self.copies = {}       # replica_id -> copy_id currently live
        self.dispatches = 0
        self.hedges = 0
        self.failovers = 0
        self.hedge_delay = None
        self.t_submit = self.t_dispatch = self.t_finish = None
        self.trace_id = None   # minted by the router at submit
        self._ncopy = 0

    @property
    def done(self):
        return self.state in ("completed", "evicted", "rejected")


class FleetRouter:
    """Front-door dispatch over a replica pool (see module docstring)."""

    def __init__(self, pool, now_fn=time.monotonic, slo=None,
                 hedge_delay=None, hedge_budget=None,
                 prefill_threshold=None, qos=None):
        from .. import config

        self.pool = pool
        self._now = now_fn
        self.qos = qos  # serving/qos.py QosPolicy (None = no QoS layer)
        self.slo = None if slo is None else float(slo)  # sync-ok: host scalar
        if hedge_delay is None:
            hedge_delay = config.get("MXT_FLEET_HEDGE_DELAY")
        self.hedge_delay = hedge_delay
        if hedge_budget is None:
            hedge_budget = config.get("MXT_FLEET_HEDGE_BUDGET")
        self.hedge_budget = hedge_budget  # None -> capacity-derived
        if prefill_threshold is None:
            prefill_threshold = config.get("MXT_FLEET_PREFILL_THRESHOLD")
        self.prefill_threshold = int(prefill_threshold)
        self._queue = collections.deque()
        self._inflight = {}   # token -> RoutedRequest
        self._by_copy = {}    # copy_id -> RoutedRequest
        self._results = {}    # token -> completed RoutedRequest (record)
        self.finished = []    # terminal requests in finish order
        self.steps = 0
        self.replays = 0
        self.stale_replies = 0

    # -- intake ------------------------------------------------------------
    def submit(self, prompt, max_new_tokens=16, deadline=None,
               eos_id=None, token=None, tenant=None, priority=None):
        """Queue one request. ``token`` is the idempotency key: a token
        whose request already COMPLETED returns the recorded
        :class:`RoutedRequest` immediately (never re-decodes); one still
        in flight returns that in-flight request (no duplicate).
        ``tenant``/``priority`` are the QoS coordinates; with a policy
        attached, admission charges the tenant's outstanding quota and
        may raise the typed OverQuotaError (an idempotent REPLAY is
        answered from the record first — it never re-charges)."""
        if token is not None:
            done = self._results.get(token)
            if done is not None:
                self.replays += 1
                _m.fleet_replays_total().inc()
                return done
            live = self._inflight.get(token)
            if live is not None:
                return live
        if self.qos is not None:
            if priority is None:
                priority = self.qos.priority_of(tenant)
            # typed OverQuotaError propagates: the request is refused
            # BEFORE it exists anywhere — nothing to clean up
            self.qos.admit(tenant, len(prompt) + int(max_new_tokens))
        rr = RoutedRequest(prompt, max_new_tokens=max_new_tokens,
                           deadline=deadline, eos_id=eos_id, token=token,
                           tenant=tenant, priority=priority)
        rr.t_submit = self._now()
        # the distributed trace starts HERE: one trace_id per routed
        # request, propagated through every dispatch, hedge duplicate,
        # failover re-enqueue, and the replicas' srv_* frames — the
        # fleet collector reassembles the span tree from it alone
        rr.trace_id = telemetry.new_trace_id()
        rr.hedge_delay = self._hedge_delay_for(rr)
        self._inflight[rr.token] = rr
        self._queue.append(rr)
        return rr

    def _hedge_delay_for(self, rr):
        if self.hedge_delay is not None:
            return float(self.hedge_delay)  # sync-ok: host config scalar
        budget = rr.deadline if rr.deadline is not None else self.slo
        return None if budget is None else 0.5 * budget

    def _span(self, rr, name, t0, t1, **attrs):
        """One router-track span/instant for ``rr``'s trace (host wall
        clocks the router already keeps — never a device read)."""
        telemetry.record_trace_span(
            name, rr.trace_id, t0, t1, clock_now=self._now(),
            track=_TRACK, token=rr.token, **attrs)

    # -- the per-tick loop -------------------------------------------------
    def step(self):
        """One router tick: apply reaper-reported deaths, fail over
        orphaned requests, dispatch the queue load-aware, hedge stalled
        requests, tick every in-process replica's batcher, collect
        completions through the fence gate, and finish drains. Returns
        True while work remains."""
        now = self._now()
        self.steps += 1
        self.pool.poll_deaths()
        self._failover_scan()
        self._dispatch_queue()
        self._hedge_scan(now)
        for h in self.pool.replicas():
            h.tick(now)
        self._poll_completions()
        self._finish_drains()
        self.pool.publish()
        return bool(self._queue or self._inflight)

    def run(self, max_steps=100000):
        """Drive until every submitted request is terminal (or the step
        bound trips). A non-empty queue with zero routable replicas and
        nothing in flight raises a typed KVStoreError instead of
        spinning."""
        while (self._queue or self._inflight) \
                and self.steps < int(max_steps):
            if self._queue and not self.pool.routable() \
                    and not any(rr.copies
                                for rr in self._inflight.values()):
                # nothing dispatched anywhere and nowhere to dispatch:
                # spinning would never finish — fail typed instead
                raise KVStoreError(
                    "serving fleet has no routable replicas for %d "
                    "queued request(s)" % len(self._queue))
            self.step()
        self.flush()
        return self.finished

    def flush(self):
        """Barrier: drain every live replica's in-flight window and
        collect what completed."""
        for h in self.pool.replicas():
            if h.state != DEAD:
                try:
                    h.flush()
                except (ConnectionError, OSError):
                    self.pool.mark_dead(h.index)
        self._poll_completions()
        # a drain that emptied on the final tick still deregisters
        self._finish_drains()
        self.pool.publish()

    # -- dispatch ----------------------------------------------------------
    def _next_queued(self):
        """Index of the next request to dispatch: the best (lowest)
        priority class, FIFO within a class — an interactive arrival
        overtakes queued bulk but never an older interactive request.
        Uniform priorities (no QoS) degrade to index 0: the historical
        pure-FIFO order, failover's front-of-queue re-enqueue intact."""
        best_i = 0
        best_p = self._queue[0].priority
        for i, rr in enumerate(self._queue):
            if rr.priority < best_p:
                best_i, best_p = i, rr.priority
        return best_i

    def _dispatch_queue(self):
        while self._queue:
            if not self.pool.routable():
                break
            i = self._next_queued()
            rr = self._queue[i]
            del self._queue[i]
            try:
                self._dispatch(rr)
            except KVStoreError:
                # no replica could take it right now: keep it queued
                # at its old position (class-FIFO order preserved)
                self._queue.insert(i, rr)
                break

    def _dispatch(self, rr, exclude=()):
        """Place one copy of ``rr``. A long prompt on a role-split pool
        goes through the disaggregated handoff (prefill tier ->
        ship_pages -> decode-tier adopt); an exhausted prefill tier
        falls back to ordinary dispatch (local prefill on the target),
        so the handoff path never loses a request."""
        if len(rr.prompt) >= self.prefill_threshold \
                and self.pool.routable(role="prefill") \
                and self.pool.routable(role="decode"):
            try:
                return self._dispatch_handoff(rr, exclude=exclude)
            except KVStoreError:
                # prefill tier gone mid-chain: the request still
                # completes — local prefill on an ordinary dispatch
                pass
        return self._dispatch_direct(rr, exclude=exclude)

    def _dispatch_direct(self, rr, exclude=()):
        """Place one copy of ``rr`` on the least-loaded routable replica
        (never one that already holds a copy), preferring the decode
        tier when the pool is role-split. Rides kv_retry: a replica
        that dies between pick and submit is marked dead and the retry
        picks a survivor; true exhaustion is a typed KVStoreError."""
        from .. import resilience

        tried = set(exclude)

        def attempt():
            h = self.pool.pick(exclude=tried | set(rr.copies),
                               role="decode")
            if h is None:
                # no decode-role replica can take it: any routable
                # replica (a prefill-only pool still serves)
                h = self.pool.pick(exclude=tried | set(rr.copies))
            if h is None:
                raise KVStoreError(
                    "no routable serving replica for request %r"
                    % (rr.token,))
            cid = "%s#%d" % (rr.token, rr._ncopy)
            try:
                state = h.submit_copy(cid, rr.prompt, rr.max_new_tokens,
                                      deadline=rr.deadline,
                                      eos_id=rr.eos_id,
                                      trace_id=rr.trace_id,
                                      tenant=rr.tenant,
                                      priority=rr.priority)
            except (ConnectionError, OSError):
                tried.add(h.index)
                self.pool.mark_dead(h.index)
                raise
            return h, cid, state

        h, cid, state = resilience.kv_retry("fleet_dispatch", rr.token,
                                            attempt)
        rr._ncopy += 1
        if state == "rejected":
            # deterministic admission reject (cannot ever fit the
            # engine): terminal, not retried
            self._finish(rr, "rejected")
            return None
        rr.copies[h.index] = cid
        self._by_copy[cid] = rr
        rr.dispatches += 1
        rr.state = "dispatched"
        now = self._now()
        if rr.t_dispatch is None:
            rr.t_dispatch = now
        _m.fleet_dispatch_total().labels(str(h.index)).inc()
        self._span(rr, "dispatch", now, now, replica=h.index, copy=cid)
        return h

    def _dispatch_handoff(self, rr, exclude=()):
        """Disaggregated dispatch: prefill ``rr`` on a prefill-tier
        replica, ship the finished KV pages over the transport, adopt
        them into a decode-tier replica — the request enters decode
        with zero prefill work on the decode tier. The whole chain is
        one kv_retry unit keyed by a STABLE copy id, so a prefill
        replica that dies mid-ship is marked dead and the retry
        re-ships from a survivor (an already-shipped copy id returns
        the cached payload — idempotent re-ship, never a re-prefill on
        the same replica)."""
        from .. import resilience

        tried = set(exclude)
        cid = "%s#%d" % (rr.token, rr._ncopy)

        def attempt():
            pf = self.pool.pick(exclude=tried, role="prefill")
            if pf is None:
                raise KVStoreError(
                    "no routable prefill replica for request %r"
                    % (rr.token,))
            t0 = self._now()
            try:
                tok0, payload = pf.ship_pages(cid, rr.prompt,
                                              rr.max_new_tokens,
                                              trace_id=rr.trace_id)
            except (ConnectionError, OSError):
                tried.add(pf.index)
                self.pool.mark_dead(pf.index)
                raise
            t1 = self._now()
            self._span(rr, "ship", t0, t1, replica=pf.index, copy=cid,
                       pages=int(payload.get("npages", 0)))
            dec = self.pool.pick(exclude=tried | set(rr.copies),
                                 role="decode")
            if dec is None:
                raise KVStoreError(
                    "no routable decode replica for request %r"
                    % (rr.token,))
            t2 = self._now()
            try:
                state = dec.adopt_copy(cid, rr.prompt,
                                       rr.max_new_tokens,
                                       deadline=rr.deadline,
                                       eos_id=rr.eos_id,
                                       trace_id=rr.trace_id,
                                       handoff=(tok0, payload),
                                       tenant=rr.tenant,
                                       priority=rr.priority)
            except (ConnectionError, OSError):
                tried.add(dec.index)
                self.pool.mark_dead(dec.index)
                raise
            t3 = self._now()
            self._span(rr, "adopt", t2, t3, replica=dec.index,
                       copy=cid, pages=int(payload.get("npages", 0)))
            return dec, state

        dec, state = resilience.kv_retry("fleet_handoff", rr.token,
                                         attempt)
        rr._ncopy += 1
        if state == "rejected":
            self._finish(rr, "rejected")
            return None
        rr.copies[dec.index] = cid
        self._by_copy[cid] = rr
        rr.dispatches += 1
        rr.state = "dispatched"
        now = self._now()
        if rr.t_dispatch is None:
            rr.t_dispatch = now
        _m.fleet_dispatch_total().labels(str(dec.index)).inc()
        self._span(rr, "dispatch", now, now, replica=dec.index,
                   copy=cid, handoff=True)
        return dec

    # -- failover ----------------------------------------------------------
    def _failover_scan(self):
        """Strip copies living on dead/fenced replicas; a request left
        with no live copy re-enqueues at the FRONT of the queue (it has
        already waited) unless its token already committed."""
        # a fenced-but-unmarked replica (the zombie verdict landed
        # between steps, its process may still be decoding): collect
        # its late replies ONE last time — every one is refused typed
        # at the accept gate, never committed — then mark it dead
        for h in self.pool.replicas():
            if h.state == DEAD or not h.fenced:
                continue
            try:
                late = h.poll()
            except (ConnectionError, OSError):
                late = []
            for cid, state, tokens in late:
                try:
                    self.accept(h, cid, state, tokens)
                except StaleReplicaError:
                    self.stale_replies += 1
                    _m.fleet_stale_replies_total().labels(
                        str(h.index)).inc()
            self.pool.mark_dead(h.index)
        for rr in list(self._inflight.values()):
            for rid, cid in list(rr.copies.items()):
                h = self.pool.get(rid)
                if h.state != DEAD and not h.fenced:
                    continue
                if h.state != DEAD:
                    self.pool.mark_dead(rid)
                del rr.copies[rid]
                self._by_copy.pop(cid, None)
                rr.failovers += 1
                _m.fleet_failovers_total().labels(str(rid)).inc()
            if not rr.copies and not rr.done \
                    and rr.token not in self._results \
                    and rr not in self._queue:
                rr.state = "queued"
                self._queue.appendleft(rr)
                now = self._now()
                self._span(rr, "failover_reenqueue", now, now,
                           failovers=rr.failovers)

    # -- hedging -----------------------------------------------------------
    def _hedge_budget(self):
        if self.hedge_budget is not None:
            return int(self.hedge_budget)
        return max(1, self.pool.total_capacity() // 4)

    def _hedge_scan(self, now):
        budget = self._hedge_budget()
        if budget <= 0:
            return
        outstanding = sum(1 for rr in self._inflight.values()
                          if len(rr.copies) > 1)
        for rr in list(self._inflight.values()):
            if outstanding >= budget:
                break
            if rr.done or len(rr.copies) != 1 or rr.hedge_delay is None \
                    or rr.t_dispatch is None \
                    or now - rr.t_dispatch <= rr.hedge_delay:
                continue
            try:
                h = self._dispatch(rr, exclude=set(rr.copies))
            except KVStoreError:
                continue  # no second replica available to hedge onto
            if h is not None:
                rr.hedges += 1
                outstanding += 1
                _m.fleet_hedges_total().labels(str(h.index)).inc()
                self._span(rr, "hedge", now, now, replica=h.index)

    # -- completion / fencing ----------------------------------------------
    def _poll_completions(self):
        for h in self.pool.replicas():
            if h.state == DEAD:
                continue  # a dead replica's replies only arrive through
                # accept(), which refuses them typed (zombie path)
            try:
                done = h.poll()
            except (ConnectionError, OSError):
                self.pool.mark_dead(h.index)
                continue
            for cid, state, tokens in done:
                try:
                    self.accept(h, cid, state, tokens)
                except StaleReplicaError:
                    self.stale_replies += 1
                    _m.fleet_stale_replies_total().labels(
                        str(h.index)).inc()
                    self.pool.mark_dead(h.index)

    def accept(self, handle, copy_id, state, tokens):
        """THE fence gate: deliver one copy's terminal state. A reply
        from a fenced replica (reaped zombie, killed, replaced) raises
        the typed :class:`StaleReplicaError` — its tokens are never
        committed; the failover copy is the only writer. Cancelled
        losers and detached copies settle silently."""
        if handle.fenced or handle.state == DEAD:
            rr = self._by_copy.get(copy_id)
            if rr is not None:
                now = self._now()
                self._span(rr, "stale_refused", now, now,
                           replica=handle.index, copy=copy_id)
            raise StaleReplicaError(
                "late reply %r from fenced serving replica %d (state "
                "%r): the request has failed over — a zombie's tokens "
                "are refused, not committed"
                % (copy_id, handle.index, handle.state))
        rr = self._by_copy.pop(copy_id, None)
        if rr is None:
            return False  # cancelled loser / drained-away copy
        for rid, cid in list(rr.copies.items()):
            if cid == copy_id:
                del rr.copies[rid]
        if rr.token in self._results:
            return False  # already committed (duplicate completion)
        if state == "completed":
            self._commit(rr, handle, tokens)
        elif state == "preempted" and not rr.copies:
            # QoS preemption is NOT a terminal outcome: the scheduler
            # freed the slot for a higher class; the request re-enqueues
            # at the BACK of the queue (it yields — failover keeps the
            # front) and replays through the same idempotent machinery,
            # so preempted bulk is late, never lost
            rr.preemptions += 1
            rr.state = "queued"
            self._queue.append(rr)
            now = self._now()
            self._span(rr, "preempt_reenqueue", now, now,
                       preemptions=rr.preemptions)
        elif state in ("evicted", "rejected") and not rr.copies:
            # every copy is gone and none completed: the SLO miss (or
            # admission reject) is the request's real outcome
            self._finish(rr, state)
        return True

    def _commit(self, rr, handle, tokens):
        rr.result = [int(t) for t in tokens]
        rr.commits += 1
        rr.committed_by = handle.index
        now = self._now()
        self._span(rr, "commit", now, now, replica=handle.index,
                   commits=rr.commits)
        # cancel losers through the replica scheduler's eviction path
        for rid, cid in list(rr.copies.items()):
            self._by_copy.pop(cid, None)
            try:
                self.pool.get(rid).cancel_copy(cid)
            except (ConnectionError, OSError):
                self.pool.mark_dead(rid)
            else:
                # the hedge loser's cancel, visible on its own right in
                # the trace (the loser replica's evicted span pairs it)
                self._span(rr, "cancel", now, now, replica=rid,
                           copy=cid)
        rr.copies.clear()
        self._results[rr.token] = rr
        self._finish(rr, "completed")

    def _finish(self, rr, outcome):
        rr.state = outcome
        rr.t_finish = self._now()
        self._inflight.pop(rr.token, None)
        if self.qos is not None:
            # refund the admission charge exactly once (every terminal
            # outcome funnels through here; replays never re-charged)
            self.qos.release(rr.tenant,
                             len(rr.prompt) + rr.max_new_tokens)
        self.finished.append(rr)
        if rr.t_submit is not None:
            self._span(rr, "request", rr.t_submit, rr.t_finish,
                       outcome=outcome, hedges=rr.hedges,
                       failovers=rr.failovers, commits=rr.commits)
        _m.fleet_requests_total().labels(outcome).inc()
        if outcome == "completed" and rr.t_submit is not None:
            _m.fleet_request_latency().observe(
                max(0.0, rr.t_finish - rr.t_submit))

    # -- drain / rejoin ----------------------------------------------------
    def drain(self, rid):
        """Graceful drain of replica ``rid``: stop routing to it,
        MIGRATE its still-queued copies back to the router (they
        re-dispatch onto peers), let running copies finish, and — once
        it is empty — deregister it cleanly (``_finish_drains``).
        Rejoin via ``pool.get(rid).rejoin()``: the replica AOT-warms
        through the shared compile cache before it is routable again.

        Only a ROUTABLE replica drains: draining one still ``warming``
        would race its go-routable transition (it would register AFTER
        the drain and serve anyway), and a second drain of an already
        draining/drained replica would re-migrate copies the first
        drain already moved — both are typed errors, not silent
        no-ops."""
        h = self.pool.get(rid)
        if h.state != ROUTABLE:
            raise MXNetError(
                "cannot drain serving replica %d in state %r: only a "
                "routable replica drains (a warming spare must finish "
                "warm-up first; a draining/drained/dead one has no "
                "admission left to stop)" % (rid, h.state))
        h.drain_start()
        try:
            queued = h.queued_copies()
        except (ConnectionError, OSError):
            self.pool.mark_dead(rid)
            return h
        for cid in queued:
            rr = self._by_copy.pop(cid, None)
            try:
                h.cancel_copy(cid)
            except (ConnectionError, OSError):
                self.pool.mark_dead(rid)
                break
            if rr is None:
                continue
            for r2, c2 in list(rr.copies.items()):
                if c2 == cid:
                    del rr.copies[r2]
            if not rr.copies and not rr.done \
                    and rr.token not in self._results:
                rr.state = "queued"
                self._queue.appendleft(rr)
        self.pool.publish()
        return h

    def _finish_drains(self):
        for h in self.pool.replicas():
            if h.state != DRAINING:
                continue
            if h.pending():
                continue
            if any(h.index in rr.copies
                   for rr in self._inflight.values()):
                continue
            h.finish_drain()
            self.pool.publish()
