"""Multi-tenant QoS for the serving fleet: admission quotas + priority
classes (the isolation half of the PR 18 control loop).

Requests carry a ``tenant`` id and a ``priority`` class. This module
owns the two policy questions the router and scheduler then enforce:

* **admission** — per-tenant quotas over OUTSTANDING work (in-flight
  requests and in-flight token budget, prompt + max_new). Over-quota
  submission raises the typed :class:`OverQuotaError` — never a silent
  drop — and counts in ``mxt_tenant_rejected_total{tenant}``. Quotas
  over outstanding work (not wall-clock rate windows) keep the policy
  deterministic under fake clocks and self-correcting: finishing a
  request refunds its budget at the router's single finish gate.
* **priority** — a small integer class, LOWER IS MORE IMPORTANT
  (interactive=0 < standard=1 < bulk=2). The router's dispatch and the
  scheduler's admission pick the best class first (FIFO within a
  class), and under slot/page pressure the scheduler PREEMPTS the most
  bulk running request to seat an interactive arrival; the preempted
  request re-enqueues through the PR 11 idempotent-failover path, so
  bulk under pressure is late, never lost.

Everything here is host bookkeeping over python ints — the lint in
tools/check_host_syncs.py scans this module: a QoS decision must never
read device state.
"""
from __future__ import annotations

import threading

from ..base import MXNetError
from . import metrics as _m

__all__ = [
    "PRIORITY_CLASSES", "OverQuotaError", "TenantSpec", "QosPolicy",
]

# canonical priority classes; lower number = more important. Unknown
# tenant names default to "standard" unless the spec pins a class.
PRIORITY_CLASSES = {"interactive": 0, "standard": 1, "bulk": 2}
_DEFAULT_TENANT = "default"


class OverQuotaError(MXNetError):
    """Typed per-tenant admission refusal (quota exhausted).

    Carries ``tenant`` so callers (traffic generators, benches, demos)
    can count refusals per tenant without parsing the message."""

    def __init__(self, msg, tenant=None):
        super(OverQuotaError, self).__init__(msg)
        self.tenant = tenant


class TenantSpec(object):
    """One tenant's policy row: priority class + outstanding quotas.

    ``priority`` defaults from the tenant's NAME when it matches a
    canonical class (an ``interactive`` tenant is class 0 without any
    extra configuration); ``max_requests`` / ``max_tokens`` of ``None``
    mean unlimited on that axis."""

    __slots__ = ("name", "priority", "max_requests", "max_tokens")

    def __init__(self, name, priority=None, max_requests=None,
                 max_tokens=None):
        self.name = str(name)
        if priority is None:
            priority = PRIORITY_CLASSES.get(
                self.name, PRIORITY_CLASSES["standard"])
        self.priority = int(priority)
        self.max_requests = None if max_requests is None \
            else int(max_requests)
        self.max_tokens = None if max_tokens is None else int(max_tokens)
        if self.max_requests is not None and self.max_requests < 1:
            raise MXNetError(
                "tenant %r: max_requests must be >= 1 (got %d) — a "
                "tenant that can never admit is a config error, not a "
                "quota" % (self.name, self.max_requests))
        if self.max_tokens is not None and self.max_tokens < 1:
            raise MXNetError(
                "tenant %r: max_tokens must be >= 1 (got %d)"
                % (self.name, self.max_tokens))

    def __repr__(self):
        return ("TenantSpec(%r, priority=%d, max_requests=%r, "
                "max_tokens=%r)" % (self.name, self.priority,
                                    self.max_requests, self.max_tokens))


class QosPolicy(object):
    """Tenant registry + admission ledger.

    The router calls :meth:`admit` before accepting a submission and
    :meth:`release` exactly once per admitted request at its single
    finish gate, so the outstanding ledger can never leak. Tenants not
    declared up front are auto-registered on first sight with the
    default quotas (``MXT_TENANT_QUOTA_REQUESTS`` /
    ``MXT_TENANT_QUOTA_TOKENS``; unset = unlimited) and a priority
    class inferred from the name."""

    def __init__(self, tenants=None, default_max_requests=None,
                 default_max_tokens=None):
        from .. import config

        if default_max_requests is None:
            default_max_requests = config.get("MXT_TENANT_QUOTA_REQUESTS")
        if default_max_tokens is None:
            default_max_tokens = config.get("MXT_TENANT_QUOTA_TOKENS")
        self.default_max_requests = default_max_requests
        self.default_max_tokens = default_max_tokens
        self._tenants = {}        # name -> TenantSpec
        self._requests = {}       # name -> outstanding request count
        self._tokens = {}         # name -> outstanding token budget
        self._lock = threading.Lock()
        for t in (tenants or ()):
            if not isinstance(t, TenantSpec):
                t = TenantSpec(t)
            self._tenants[t.name] = t

    # -- construction --------------------------------------------------------
    @classmethod
    def parse(cls, spec, **kwargs):
        """Build a policy from a compact CLI spec: tenant names
        separated by ``:`` or ``,``, each optionally ``name=class``
        (class = canonical name or integer). ``interactive:bulk`` gives
        two tenants in classes 0 and 2."""
        policy = cls(**kwargs)
        for part in str(spec).replace(",", ":").split(":"):
            part = part.strip()
            if not part:
                continue
            prio = None
            if "=" in part:
                part, _, cls_name = part.partition("=")
                part = part.strip()
                cls_name = cls_name.strip()
                if cls_name in PRIORITY_CLASSES:
                    prio = PRIORITY_CLASSES[cls_name]
                else:
                    try:
                        prio = int(cls_name)
                    except ValueError:
                        raise MXNetError(
                            "tenant spec %r: class %r is neither a "
                            "canonical class (%s) nor an integer"
                            % (spec, cls_name,
                               "/".join(sorted(PRIORITY_CLASSES))))
            policy.add_tenant(part, priority=prio)
        if not policy.tenants():
            raise MXNetError("tenant spec %r declares no tenants" % spec)
        return policy

    def add_tenant(self, name, priority=None, max_requests=None,
                   max_tokens=None):
        spec = TenantSpec(
            name, priority=priority,
            max_requests=self.default_max_requests
            if max_requests is None else max_requests,
            max_tokens=self.default_max_tokens
            if max_tokens is None else max_tokens)
        with self._lock:
            self._tenants[spec.name] = spec
        return spec

    def tenants(self):
        with self._lock:
            return sorted(self._tenants)

    def _spec(self, tenant):
        """Resolve (auto-registering unknowns). Caller holds no lock."""
        name = _DEFAULT_TENANT if tenant is None else str(tenant)
        with self._lock:
            spec = self._tenants.get(name)
        if spec is None:
            spec = self.add_tenant(name)
        return spec

    def priority_of(self, tenant):
        """The tenant's priority class (auto-registers unknowns)."""
        return self._spec(tenant).priority

    # -- admission ledger ----------------------------------------------------
    def admit(self, tenant, tokens):
        """Charge one request + ``tokens`` budget against the tenant's
        outstanding quota; raises :class:`OverQuotaError` (and counts
        the rejection) when either axis is exhausted."""
        spec = self._spec(tenant)
        tokens = int(tokens)
        with self._lock:
            nreq = self._requests.get(spec.name, 0)
            ntok = self._tokens.get(spec.name, 0)
            if spec.max_requests is not None \
                    and nreq + 1 > spec.max_requests:
                _m.tenant_rejected_total().labels(spec.name).inc()
                raise OverQuotaError(
                    "tenant %r over request quota: %d outstanding of "
                    "max %d — finish or cancel in-flight work before "
                    "submitting more (typed refusal, the request was "
                    "NOT enqueued)" % (spec.name, nreq,
                                       spec.max_requests),
                    tenant=spec.name)
            if spec.max_tokens is not None \
                    and ntok + tokens > spec.max_tokens:
                _m.tenant_rejected_total().labels(spec.name).inc()
                raise OverQuotaError(
                    "tenant %r over token quota: %d outstanding + %d "
                    "requested > max %d (typed refusal, the request "
                    "was NOT enqueued)" % (spec.name, ntok, tokens,
                                           spec.max_tokens),
                    tenant=spec.name)
            self._requests[spec.name] = nreq + 1
            self._tokens[spec.name] = ntok + tokens
        _m.tenant_admitted_total().labels(spec.name).inc()
        _m.tenant_inflight().labels(spec.name).set(nreq + 1)
        return spec

    def release(self, tenant, tokens):
        """Refund one finished request's charge (router finish gate)."""
        spec = self._spec(tenant)
        with self._lock:
            nreq = max(0, self._requests.get(spec.name, 0) - 1)
            ntok = max(0, self._tokens.get(spec.name, 0) - int(tokens))
            self._requests[spec.name] = nreq
            self._tokens[spec.name] = ntok
        _m.tenant_inflight().labels(spec.name).set(nreq)

    def outstanding(self, tenant):
        """(requests, tokens) currently charged to the tenant."""
        name = _DEFAULT_TENANT if tenant is None else str(tenant)
        with self._lock:
            return (self._requests.get(name, 0), self._tokens.get(name, 0))
