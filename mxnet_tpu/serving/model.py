"""Decode-model adapter — what the serving engine needs from a model.

The engine is model-agnostic: anything exposing the small surface below
(:class:`TinyDecoder` is the canonical implementation and the
test/bench/example workhorse) can serve through the paged KV cache:

- ``embed(params, tokens, positions)`` — token + position embedding for
  ONE token per sequence (decode) or a whole prompt (prefill);
- ``layer_qkv(params, l, h)`` — layer ``l``'s pre-attention projection,
  returning per-head q/k/v;
- ``layer_finish(params, l, h, attn)`` — attention output projection,
  residual, and the MLP for layer ``l``;
- ``logits(params, h)`` — final norm + (tied) LM head;
- ``prefill(params, tokens, valid_length)`` — the dense prompt pass:
  per-layer K/V for every prompt position plus the last valid
  position's logits. Prefill attention is causal+ragged DENSE
  (the flash path's reference with a padding bias); decode attention is
  the paged kernel — both mask with the same definition, which is what
  the parity tests pin.

Everything is pure JAX on pytrees of arrays (no gluon Blocks): the
serving decode step must trace into ONE donated jit program, and
parameter dicts keep that trivially true.

:class:`TinyDecoder` is a standard pre-LN causal transformer LM (tied
embeddings, GELU MLP). :meth:`reference_decode` greedy-decodes by
re-running the dense prefill over the whole growing sequence each step —
quadratic and cache-free on purpose: it is the end-to-end oracle the
paged engine must reproduce token for token.
"""
from __future__ import annotations

import math

import numpy as np

__all__ = ["TinyDecoder"]


class TinyDecoder:
    """A small pure-JAX causal transformer LM for the serving stack."""

    def __init__(self, vocab=128, num_layers=2, num_heads=2, head_dim=16,
                 ffn_hidden=None, max_len=1024):
        self.vocab = int(vocab)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.model_dim = self.num_heads * self.head_dim
        self.ffn_hidden = int(ffn_hidden or 4 * self.model_dim)
        self.max_len = int(max_len)
        self.sm_scale = 1.0 / math.sqrt(self.head_dim)

    # -- parameters -------------------------------------------------------
    def init_params(self, seed=0):
        import jax.numpy as jnp

        rng = np.random.RandomState(seed)
        m, f = self.model_dim, self.ffn_hidden

        def w(*shape):
            return jnp.asarray(
                rng.normal(0.0, 0.02, shape).astype(np.float32))

        p = {"wte": w(self.vocab, m), "wpe": w(self.max_len, m),
             "lnf_g": jnp.ones((m,), jnp.float32),
             "lnf_b": jnp.zeros((m,), jnp.float32)}
        for l in range(self.num_layers):
            p["ln1_%d_g" % l] = jnp.ones((m,), jnp.float32)
            p["ln1_%d_b" % l] = jnp.zeros((m,), jnp.float32)
            p["qkv_%d" % l] = w(m, 3 * m)
            p["o_%d" % l] = w(m, m)
            p["ln2_%d_g" % l] = jnp.ones((m,), jnp.float32)
            p["ln2_%d_b" % l] = jnp.zeros((m,), jnp.float32)
            p["fc1_%d" % l] = w(m, f)
            p["fc2_%d" % l] = w(f, m)
        return p

    def truncated(self, params, num_layers):
        """A layer-truncated DRAFT of this model: same geometry, the
        first ``num_layers`` transformer layers, shared embeddings and
        final norm. Greedy streams of a truncated prefix agree with the
        full model on most steps (repetitive greedy attractors), which
        is what makes it a useful speculative draft without any
        training. Returns ``(draft_model, draft_params)`` — the params
        are the SAME arrays (zero extra device bytes)."""
        num_layers = int(num_layers)
        if not 1 <= num_layers <= self.num_layers:
            raise ValueError("draft layers must be in [1, %d], got %d"
                             % (self.num_layers, num_layers))
        draft = TinyDecoder(vocab=self.vocab, num_layers=num_layers,
                            num_heads=self.num_heads,
                            head_dim=self.head_dim,
                            ffn_hidden=self.ffn_hidden,
                            max_len=self.max_len)
        keep = {"wte", "wpe", "lnf_g", "lnf_b"}
        dp = {}
        for key, val in params.items():
            base = key.split("_")[0]
            if key in keep:
                dp[key] = val
            elif base in ("ln1", "ln2", "qkv", "o", "fc1", "fc2"):
                layer = int(key.split("_")[1])
                if layer < num_layers:
                    dp[key] = val
        return draft, dp

    # -- weight-only int8 quantization ------------------------------------
    _WOQ_KEYS = ("qkv", "o", "fc1", "fc2")

    def quantize_params(self, params, resolve=None):
        """Weight-only int8 quantization of the decode matmuls: each
        eligible weight (qkv/o/fc1/fc2 per layer) is replaced by an
        ``<name>__q`` int8 matrix + ``<name>__s`` per-column amax when
        the per-shape routing decision says the quantized kernel wins
        there — by default :func:`tuning.resolve_quant` (table hit,
        else the heuristic cost model; measured entries win on
        device). Tied embeddings stay f32 (they also feed lookups).

        Returns ``(new_params, report)`` with report mapping weight key
        to the backend chosen."""
        from .. import tuning
        from ..ops import quantization as Q

        resolve = resolve or (lambda k_, n_: tuning.resolve_quant(
            "woq_matmul", k_, n_, "float32"))
        out, report = {}, {}
        for key, val in params.items():
            base = key.split("_")[0]
            if base in self._WOQ_KEYS and getattr(val, "ndim", 0) == 2:
                ent = resolve(int(val.shape[0]), int(val.shape[1]))
                backend = ent.get("backend", "fp") \
                    if isinstance(ent, dict) else str(ent)
                report[key] = backend
                if backend == "int8":
                    q, amax = Q.quantize_rowwise(val)
                    out[key + "__q"] = q
                    out[key + "__s"] = amax
                    continue
            out[key] = val
        return out, report

    def _mm(self, params, name, x):
        """One decode matmul, routed: the weight-only-quantized kernel
        when ``quantize_params`` stored this weight as int8, the plain
        f32 matmul otherwise. Trace-time branch — zero runtime cost."""
        if name + "__q" in params:
            from ..ops import quantization as Q

            return Q.woq_matmul(x, params[name + "__q"],
                                params[name + "__s"])
        return x @ params[name]

    # -- shared layer math (identical trace for prefill and decode) -------
    @staticmethod
    def _ln(x, g, b):
        import jax.numpy as jnp

        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
        return (x - mu) * (1.0 / jnp.sqrt(var + 1e-5)) * g + b

    def embed(self, params, tokens, positions):
        """(..., ) int tokens/positions -> (..., M) hidden."""
        return params["wte"][tokens] + params["wpe"][positions]

    def layer_qkv(self, params, l, h):
        """(..., M) hidden -> q, k, v each (..., H, D)."""
        import jax.numpy as jnp

        x = self._ln(h, params["ln1_%d_g" % l], params["ln1_%d_b" % l])
        qkv = self._mm(params, "qkv_%d" % l, x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = q.shape[:-1] + (self.num_heads, self.head_dim)
        return q.reshape(shape), k.reshape(shape), v.reshape(shape)

    def layer_finish(self, params, l, h, attn):
        """attn (..., H, D) -> next hidden (..., M)."""
        import jax

        m = self.model_dim
        h = h + self._mm(params, "o_%d" % l,
                         attn.reshape(attn.shape[:-2] + (m,)))
        x = self._ln(h, params["ln2_%d_g" % l], params["ln2_%d_b" % l])
        return h + self._mm(
            params, "fc2_%d" % l,
            jax.nn.gelu(self._mm(params, "fc1_%d" % l, x)))

    def logits(self, params, h):
        return self._ln(h, params["lnf_g"], params["lnf_b"]) \
            @ params["wte"].T

    # -- dense prompt pass ------------------------------------------------
    def prefill(self, params, tokens, valid_length):
        """Dense causal+ragged prompt pass.

        ``tokens``: (B, T) int32 (right-padded), ``valid_length``: (B,).
        Returns ``(k, v, last_logits)`` with k/v ``(L, B, H, T, D)`` and
        ``last_logits`` ``(B, vocab)`` taken at each sequence's last
        valid position — the logits that sample generated token #1.
        """
        import jax.numpy as jnp

        from ..ops import attention as A

        B, T = tokens.shape
        h = self.embed(params, tokens, jnp.arange(T)[None, :])
        ks, vs = [], []
        bias = A.make_padding_bias(valid_length, max_len=T,
                                   dtype="float32")
        for l in range(self.num_layers):
            q, k, v = self.layer_qkv(params, l, h)      # (B, T, H, D)
            qt = jnp.transpose(q, (0, 2, 1, 3))         # (B, H, T, D)
            kt = jnp.transpose(k, (0, 2, 1, 3))
            vt = jnp.transpose(v, (0, 2, 1, 3))
            ks.append(kt)
            vs.append(vt)
            attn = A._attention_reference(qt, kt, vt, bias, True,
                                          self.sm_scale)
            h = self.layer_finish(params, l, h,
                                  jnp.transpose(attn, (0, 2, 1, 3)))
        last = jnp.clip(valid_length.astype(jnp.int32) - 1, 0, T - 1)
        h_last = jnp.take_along_axis(
            h, last[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        return (jnp.stack(ks), jnp.stack(vs),
                self.logits(params, h_last))

    def prefill_with_prefix(self, params, tokens, valid_length, start,
                            k_prefix, v_prefix):
        """Suffix prompt pass against a REUSED prefix: the serving
        engine found tokens ``[0, start)`` already resident as shared
        KV pages (serving/prefix.py), so only the suffix runs through
        the layers — admission cost drops proportionally to prefix
        coverage.

        ``tokens``: (B, Tsuf) right-padded SUFFIX tokens,
        ``valid_length``: (B,) valid suffix lengths, ``start``: scalar
        int32 absolute position of the first suffix token,
        ``k_prefix``/``v_prefix``: (L, Tpre_pad, H, D) float prefix K/V
        gathered (and dequantized) from the pool — positions at or past
        ``start`` in the padded gather are masked out, so a page-padded
        gather and the full-match copy-on-write case (``start = T-1``
        recomputing only the final token) are both correct.

        Returns ``(k, v, last_logits)`` for the SUFFIX only — k/v
        ``(L, B, H, Tsuf, D)``, exactly :meth:`prefill`'s layout, ready
        for the page scatter."""
        import jax.numpy as jnp

        from ..ops import attention as A

        B, Tsuf = tokens.shape
        Tpre = k_prefix.shape[1]
        neg = A._NEG_INF
        start = jnp.asarray(start, jnp.int32)
        h = self.embed(params, tokens,
                       start + jnp.arange(Tsuf)[None, :])
        # bias (B, 1, Tsuf, Tpre + Tsuf): prefix columns open below
        # `start`, suffix columns causal within the suffix AND below
        # the ragged valid length
        pre_open = jnp.where(jnp.arange(Tpre)[None, :] < start,
                             0.0, neg)                     # (1, Tpre)
        pre_open = jnp.broadcast_to(pre_open, (Tsuf, Tpre))
        rows = jnp.arange(Tsuf)
        causal = jnp.where(rows[None, :] <= rows[:, None], 0.0, neg)
        mask = jnp.concatenate([pre_open, causal], axis=1)  # (Tsuf, Ttot)
        ragged = jnp.where(
            rows[None, :] < valid_length.astype(jnp.int32)[:, None],
            0.0, neg)                                      # (B, Tsuf)
        bias = mask[None, None] + jnp.concatenate(
            [jnp.zeros((B, Tpre), jnp.float32), ragged],
            axis=1)[:, None, None, :]
        kpre = jnp.transpose(k_prefix, (0, 2, 1, 3))  # (L, H, Tpre, D)
        vpre = jnp.transpose(v_prefix, (0, 2, 1, 3))
        ks, vs = [], []
        for l in range(self.num_layers):
            q, k, v = self.layer_qkv(params, l, h)      # (B, Tsuf, H, D)
            qt = jnp.transpose(q, (0, 2, 1, 3))         # (B, H, Tsuf, D)
            kt = jnp.transpose(k, (0, 2, 1, 3))
            vt = jnp.transpose(v, (0, 2, 1, 3))
            ks.append(kt)
            vs.append(vt)
            kcat = jnp.concatenate(
                [jnp.broadcast_to(kpre[l][None],
                                  (B,) + kpre[l].shape), kt], axis=2)
            vcat = jnp.concatenate(
                [jnp.broadcast_to(vpre[l][None],
                                  (B,) + vpre[l].shape), vt], axis=2)
            attn = A._attention_reference(qt, kcat, vcat, bias, False,
                                          self.sm_scale)
            h = self.layer_finish(params, l, h,
                                  jnp.transpose(attn, (0, 2, 1, 3)))
        last = jnp.clip(valid_length.astype(jnp.int32) - 1, 0, Tsuf - 1)
        h_last = jnp.take_along_axis(
            h, last[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        return (jnp.stack(ks), jnp.stack(vs),
                self.logits(params, h_last))

    # -- the cache-free oracle -------------------------------------------
    def reference_decode(self, params, prompt, max_new_tokens,
                         eos_id=None):
        """Greedy-decode by re-running the DENSE prompt pass over the
        whole growing sequence every step — no KV cache, no paging, no
        deferred reads. Quadratic and slow by design: the independent
        end-to-end oracle the paged serving engine must match token for
        token. The growing sequence is right-padded to one fixed bucket
        (valid_length masks the tail), so the whole loop traces a
        single shape instead of one per length."""
        import jax.numpy as jnp

        import jax

        toks = [int(t) for t in prompt]
        out = []
        bucket = -(-(len(toks) + int(max_new_tokens)) // 32) * 32
        fwd = jax.jit(self.prefill)
        for _ in range(int(max_new_tokens)):
            arr = np.zeros((1, bucket), np.int32)
            arr[0, :len(toks)] = toks
            vl = jnp.asarray(np.array([len(toks)], np.int32))
            _, _, logits = fwd(params, jnp.asarray(arr), vl)
            # sync-ok: the oracle reads every step by definition
            nxt = int(np.argmax(np.array(logits[0])))
            out.append(nxt)
            toks.append(nxt)
            if eos_id is not None and nxt == int(eos_id):
                break
        return out
