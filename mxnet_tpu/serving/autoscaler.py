"""SLO-driven fleet autoscaler — the actuator that closes the PR 13
observability loop.

Everything the fleet already EXPORTS (p99 latency, queue depth,
replica occupancy, goodput — the FleetCollector's merged page) becomes
an input here, and every lever the fleet already HAS becomes an
actuator:

* **grow** — spawn a hot spare through the PR 11 warming→routable
  lifecycle: build the engine, AOT-warm it off the shared compile cache
  (zero request-path compiles, the PR 6 contract), and only then
  register + route. The spawn is split :meth:`LocalReplica.prepare` /
  ``go_routable`` so a SLOW-warming spare (``replica_spawn_slow``)
  holds in ``warming`` without ever stalling the router's step — the
  autoscaler promotes it from its own loop when warm-up completes.
* **shrink** — ``router.drain``: stop admission, migrate queued copies
  to peers, finish running ones, deregister gracefully. Only a
  ROUTABLE replica is ever drained (the PR 18 lifecycle-race bugfix).
* **decode-worker fleets** — :meth:`DecodeWorkerFleet.resize`, driven
  independently by the fleet's own buffer watermarks (a starved
  consumer grows the fleet, a producer running far ahead shrinks it).
* **prefill/decode tiers** — on a role-split pool (PR 16), growth goes
  to the hotter tier (mean per-replica occupancy from the merged page).

Control discipline — the loop must never flap and never lie:

* hysteresis: scale UP on a hot signal (p99 over the SLO, or queue
  backlog past ``MXT_AUTOSCALE_QUEUE_HIGH`` × capacity); scale DOWN
  only after ``MXT_AUTOSCALE_CALM_TICKS`` consecutive calm evaluations
  (empty queue, occupancy under ``MXT_AUTOSCALE_OCC_LOW``, p99 within
  SLO). One hot sample resets the calm streak.
* cooldown: ``MXT_AUTOSCALE_COOLDOWN`` seconds between actions, and at
  most one spare warming at a time.
* typed floor/ceiling: the loop clamps; an EXPLICIT ``scale_to`` below
  ``min_replicas`` (or above ``max_replicas``) raises
  :class:`AutoscalerError` and counts a ``refused`` event.
* every decision is a replica-lifecycle event on the PR 13 trace
  timeline (``scale_up``/``scale_down`` spans on the autoscaler's own
  track + ``mxt_autoscale_events_total{direction}``), so a Perfetto
  load of the fleet trace shows WHEN the fleet grew and WHY.

Decisions are host arithmetic over metrics snapshots and wall clocks —
tools/check_host_syncs.py scans this module; reading device state to
decide a scale action would re-serialize the very fleet it grows.

:class:`TrafficGenerator` lives here too: the seeded open-loop arrival
process the flash-crowd chaos cells and the ``autoscale_ab`` bench
drive, consulting the ``traffic_storm:rps=N,after=K[,tenant=T]`` fault
rule so every storm is deterministic per ``MXT_CHAOS_SEED``.
"""
from __future__ import annotations

import threading

from ..base import MXNetError
from . import metrics as _m
from .fleet import WARMING, LocalReplica

__all__ = ["AutoscalerError", "FleetAutoscaler", "TrafficGenerator"]

_TRACK = "autoscaler"

# decode-worker fleet watermarks: fraction of the host-side batch
# buffer. Empty buffer = the consumer is starving (grow the fleet);
# near-full = the producers run far ahead (shrink it).
_WORKER_LOW = 0.25
_WORKER_HIGH = 0.75

# p99 is read from the fleet-wide request latency histogram
_LATENCY_METRIC = "mxt_fleet_request_latency_seconds"
_OCC_METRIC = "mxt_fleet_replica_occupancy"
_REQS_METRIC = "mxt_fleet_requests_total"


class AutoscalerError(MXNetError):
    """Typed refusal of a scale action (below the configured floor,
    above the ceiling, or an actuator in an unusable state)."""


class FleetAutoscaler:
    """The control loop. ``step()`` runs one evaluation synchronously
    (what the tests and the bench drive, deterministic under a fake
    ``now_fn``); ``start(interval)`` runs it on a daemon thread like
    the FleetCollector's background scrape.

    ``engine_factory`` is the same callable the fleet was built from —
    a spawned spare AOT-warms off the shared compile cache, so growth
    is cheap by construction (the arXiv 2604.15464 economics)."""

    def __init__(self, router, engine_factory, collector=None,
                 now_fn=None, slo=None, min_replicas=None,
                 max_replicas=None, cooldown=None, queue_high=None,
                 occ_low=None, calm_ticks=None, warm=True,
                 heartbeats=True, worker_fleets=()):
        from .. import config, telemetry

        self.router = router
        self.pool = router.pool
        self._factory = engine_factory
        self._now = now_fn if now_fn is not None else router._now
        self._warm = bool(warm)
        self._heartbeats = bool(heartbeats)
        if slo is None:
            slo = getattr(router, "slo", None)
        if slo is None:
            slo = config.get("MXT_AUTOSCALE_SLO")
        self.slo = slo
        self.min_replicas = int(config.get("MXT_AUTOSCALE_MIN_REPLICAS")
                                if min_replicas is None else min_replicas)
        self.max_replicas = int(config.get("MXT_AUTOSCALE_MAX_REPLICAS")
                                if max_replicas is None else max_replicas)
        if self.min_replicas < 1:
            raise AutoscalerError(
                "autoscaler floor must be >= 1 replica (got %d) — a "
                "fleet scaled to zero cannot serve the request that "
                "would scale it back up" % self.min_replicas)
        if self.max_replicas < self.min_replicas:
            raise AutoscalerError(
                "autoscaler ceiling %d is below its floor %d"
                % (self.max_replicas, self.min_replicas))
        self.cooldown = config.get("MXT_AUTOSCALE_COOLDOWN") \
            if cooldown is None else cooldown
        self.queue_high = config.get("MXT_AUTOSCALE_QUEUE_HIGH") \
            if queue_high is None else queue_high
        self.occ_low = config.get("MXT_AUTOSCALE_OCC_LOW") \
            if occ_low is None else occ_low
        self.calm_ticks = int(config.get("MXT_AUTOSCALE_CALM_TICKS")
                              if calm_ticks is None else calm_ticks)
        self._collector = collector
        self._own_collector = False
        if self._collector is None:
            from .. import telemetry_fleet

            self._collector = telemetry_fleet.FleetCollector(
                server=self.pool.server,
                coordinator=None if self.pool.server is not None
                else self.pool.coordinator,
                include_local=True, now_fn=self._now)
            self._own_collector = True
        # the autoscaler's OWN trace: scale decisions + spare promotions
        # land here so the Perfetto fleet timeline shows the control
        # loop next to the request tracks
        self.trace_id = telemetry.new_trace_id()
        self.decisions = []      # decision records, oldest first
        self._ndecisions = 0
        self._last_action = None  # time of the last actuation (cooldown)
        self._calm = 0            # consecutive calm evaluations
        self._pending = []        # (handle, ready_at): spares warming
        self._worker_fleets = list(worker_fleets)
        self._worker_last = {}    # id(fleet) -> last actuation time
        self._thread = None
        self._stop = threading.Event()
        _m.autoscale_target_replicas().set(
            len(self.pool.routable()) + len(self._pending))

    # -- signals -------------------------------------------------------------
    def signals(self):
        """One merged-fleet-page snapshot reduced to the loop's inputs:
        p99 vs SLO, queue backlog (router + replica queues), occupancy
        per slot, and goodput. Pure host arithmetic — missing metrics
        (no traffic yet) read as ``None``/zero, never an error."""
        reg = self._collector.scrape().fleet_registry()
        p99 = reg.quantile(_LATENCY_METRIC, 0.99, missing_ok=True)
        queue = len(self.router._queue)
        rq = reg.merged_value("mxt_serving_queue_depth")
        if rq:
            queue += int(rq)
        occ = reg.merged_value(_OCC_METRIC) or 0
        cap = max(1, self.pool.total_capacity())
        done = reg.merged_value(_REQS_METRIC,
                                labels={"outcome": "completed"}) or 0
        bad = 0
        for outcome in ("evicted", "rejected"):
            bad += reg.merged_value(_REQS_METRIC,
                                    labels={"outcome": outcome}) or 0
        goodput = done / (done + bad) if (done + bad) else None
        return {"p99": p99, "queue": queue, "occupancy": occ / cap,
                "capacity": cap, "goodput": goodput}

    # -- the loop ------------------------------------------------------------
    def step(self):
        """One control evaluation: promote any warmed spare, read the
        merged page, decide, actuate. Returns the decision direction
        (``"up"``/``"down"``) or ``None`` (hold)."""
        now = self._now()
        self.promote_spares(now)
        sig = self.signals()
        decision = self._decide(sig, now)
        if decision == "up":
            self._scale_up(sig, now)
        elif decision == "down":
            self._scale_down(sig, now)
        self._step_workers(now)
        _m.autoscale_target_replicas().set(self.replica_target())
        return decision

    def replica_target(self):
        """Replicas the loop currently stands behind: routable +
        draining-out excluded, warming spares included."""
        return len(self.pool.routable()) + len(self._pending)

    def _decide(self, sig, now):
        hot = sig["queue"] >= self.queue_high * sig["capacity"]
        if not hot and self.slo is not None and sig["p99"] is not None:
            hot = sig["p99"] > self.slo
        calm = (sig["queue"] == 0 and sig["occupancy"] <= self.occ_low
                and (self.slo is None or sig["p99"] is None
                     or sig["p99"] <= self.slo))
        if hot:
            self._calm = 0   # hysteresis: one hot sample resets calm
        elif calm:
            self._calm += 1
        if self._last_action is not None \
                and now - self._last_action < self.cooldown:
            return None
        target = self.replica_target()
        if hot and not self._pending and target < self.max_replicas:
            return "up"
        if not hot and calm and self._calm >= self.calm_ticks \
                and target > self.min_replicas:
            return "down"
        return None

    # -- actuators -----------------------------------------------------------
    def _next_index(self):
        return 1 + max((h.index for h in self.pool.replicas()),
                       default=-1)

    def _growth_role(self):
        """On a role-split pool, grow the hotter tier (mean per-replica
        occupancy from the merged page); plain pools grow decode."""
        pf = self.pool.routable(role="prefill")
        if not pf:
            return "decode"
        reg = self._collector.fleet_registry()

        def mean_occ(handles):
            occ = cap = 0
            for h in handles:
                occ += reg.merged_value(
                    _OCC_METRIC, labels={"replica": str(h.index)}) or 0
                cap += max(1, int(h.capacity or 1))
            return occ / max(1, cap)

        dec = [h for h in self.pool.routable()
               if getattr(h, "role", "decode") != "prefill"]
        return "prefill" if mean_occ(pf) > mean_occ(dec) else "decode"

    def _scale_up(self, sig, now, role=None):
        """Spawn one spare: prepare (build + AOT-warm) now, join the
        pool WARMING, go routable when warm-up completes — immediately,
        unless the seeded ``replica_spawn_slow:ms=N`` rule holds it
        (the router keeps serving off the existing replicas either
        way)."""
        from .. import resilience

        if role is None:
            role = self._growth_role()
        idx = self._next_index()
        h = LocalReplica(idx, self._factory,
                         coordinator=self.pool.coordinator,
                         now_fn=self._now, heartbeats=self._heartbeats,
                         role=role)
        h.prepare(warm=self._warm)
        delay = 0.0
        inj = resilience.fault_point()
        rule = inj.rule("replica_spawn_slow")
        if rule is not None and inj.should("replica_spawn_slow"):
            delay = int(rule.get("ms", 100)) / 1e3
        self.pool.add(h)
        self._pending.append((h, now + delay))
        self._record("up", now, replica=idx, role=role,
                     reason=self._reason(sig), delay=delay)
        self.promote_spares(now)

    def _scale_down(self, sig, now):
        """Drain one routable replica: the least-loaded (the cheapest
        to migrate), highest index on ties (spares retire before the
        seed fleet). Refuses typed at the floor."""
        candidates = self.pool.routable()
        if self.replica_target() <= self.min_replicas:
            self._record("refused", now, reason="at floor (%d)"
                         % self.min_replicas)
            raise AutoscalerError(
                "cannot scale below the configured floor of %d "
                "replica(s) — raise min_replicas/MXT_AUTOSCALE_MIN_"
                "REPLICAS if a smaller fleet is really intended"
                % self.min_replicas)

        def load_of(h):
            try:
                ld = h.load()
                return int(ld.get("queue", 0)) + int(ld.get("active", 0))
            except (ConnectionError, OSError):
                return 0

        victim = min(candidates, key=lambda h: (load_of(h), -h.index))
        self.router.drain(victim.index)
        self._record("down", now, replica=victim.index,
                     reason=self._reason(sig))

    def promote_spares(self, now=None):
        """Flip warmed spares to routable (their warm-up horizon
        passed); the slow-spawn rule only ever delays THIS promotion,
        never the router. Returns the indices promoted."""
        now = self._now() if now is None else now
        out = []
        still = []
        for h, ready_at in self._pending:
            if h.state != WARMING:   # killed while warming
                continue
            if now >= ready_at:
                h.go_routable()
                out.append(h.index)
                self._span("replica_routable", now, replica=h.index)
            else:
                still.append((h, ready_at))
        self._pending = still
        if out:
            self.pool.publish()
        return out

    def scale_to(self, n, reason="manual"):
        """Explicit fleet size: clamps NOTHING — below the floor or
        above the ceiling is a typed refusal (and a ``refused`` event),
        exactly so an operator typo cannot black-hole the fleet."""
        n = int(n)
        now = self._now()
        if n < self.min_replicas or n > self.max_replicas:
            self._record("refused", now, reason="%s: %d outside [%d, %d]"
                         % (reason, n, self.min_replicas,
                            self.max_replicas))
            raise AutoscalerError(
                "scale_to(%d) refused: outside the configured bounds "
                "[%d, %d]" % (n, self.min_replicas, self.max_replicas))
        guard = 0
        while self.replica_target() < n and guard < 64:
            self._scale_up(None, self._now())
            guard += 1
        while self.replica_target() > n and guard < 64:
            self._scale_down(None, self._now())
            guard += 1
        return self.replica_target()

    def attach_worker_fleet(self, fleet):
        """Register a :class:`~mxnet_tpu.data_plane.workers.
        DecodeWorkerFleet` for independent scaling off its own buffer
        watermarks."""
        self._worker_fleets.append(fleet)
        return fleet

    def _step_workers(self, now):
        """Independent decode-worker scaling: one worker at a time per
        fleet, its own cooldown, floor of 1 enforced typed by
        ``resize`` itself."""
        for wf in self._worker_fleets:
            q = getattr(wf, "_q", None)
            if q is None or not getattr(q, "maxsize", 0):
                continue
            last = self._worker_last.get(id(wf))
            if last is not None and now - last < self.cooldown:
                continue
            fill = q.qsize() / q.maxsize
            if fill <= _WORKER_LOW and wf.live_workers() >= \
                    wf.num_workers:
                wf.resize(wf.num_workers + 1)
                self._worker_last[id(wf)] = now
                self._record("workers_up", now, workers=wf.num_workers,
                             reason="buffer %.0f%% full" % (100 * fill))
            elif fill >= _WORKER_HIGH and wf.num_workers > 1:
                wf.resize(wf.num_workers - 1)
                self._worker_last[id(wf)] = now
                self._record("workers_down", now,
                             workers=wf.num_workers,
                             reason="buffer %.0f%% full" % (100 * fill))

    # -- bookkeeping ---------------------------------------------------------
    @staticmethod
    def _reason(sig):
        if sig is None:
            return "explicit"
        return ("queue=%d occ=%.2f p99=%s"
                % (sig["queue"], sig["occupancy"],
                   "-" if sig["p99"] is None else
                   "%.3fs" % sig["p99"]))

    def _span(self, name, now, **attrs):
        from .. import telemetry

        telemetry.record_trace_span(name, self.trace_id, now, now,
                                    clock_now=now, track=_TRACK, **attrs)

    def _record(self, direction, now, reason=None, **attrs):
        from .. import diagnostics

        self._ndecisions += 1
        rec = dict(attrs)
        rec.update({"direction": direction, "at": now, "reason": reason,
                    "seq": self._ndecisions})
        self.decisions.append(rec)
        if direction in ("up", "down"):
            self._last_action = now
            self._calm = 0
        _m.autoscale_events_total().labels(direction).inc()
        _m.autoscale_last_decision().labels(direction).set(
            self._ndecisions)
        self._span("scale_" + direction, now, reason=reason, **attrs)
        diagnostics.record_event("autoscale_" + direction,
                                 reason=reason, **attrs)

    # -- background loop -----------------------------------------------------
    def start(self, interval=None):
        """Run the loop on a daemon thread every ``interval`` seconds
        (default ``MXT_AUTOSCALE_INTERVAL``) — the deployment shape;
        tests and the bench call :meth:`step` synchronously instead."""
        from .. import config

        if interval is None:
            interval = config.get("MXT_AUTOSCALE_INTERVAL")
        interval = float(interval)  # sync-ok: host config scalar
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def _loop():
            while not self._stop.wait(interval):
                try:
                    self.step()
                except Exception:  # noqa: BLE001 — the control loop
                    pass           # must never take the fleet down

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="mxt-autoscaler")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def close(self):
        self.stop()
        if self._own_collector:
            self._collector.close()


class TrafficGenerator:
    """Seeded open-loop arrival process over a :class:`FleetRouter` —
    the load half of the flash-crowd story. A credit accumulator turns
    (rate × elapsed) into whole submissions per :meth:`tick`, prompts
    come from a seeded RNG, and the ``traffic_storm:rps=N,after=K
    [,tenant=T]`` fault rule flips the rate to ``N`` after the Kth tick
    (tagging storm traffic with tenant ``T``) — deterministically per
    ``MXT_CHAOS_SEED``, like every other chaos rule. Typed over-quota
    refusals are COUNTED, never dropped silently."""

    def __init__(self, router, rate=10.0, seed=0, vocab=64,
                 prompt_len=(4, 12), max_new_tokens=6, deadline=None,
                 tenants=None, max_requests=None, prefix="tg"):
        import numpy as np

        self.router = router
        self.rate = rate
        self.vocab = int(vocab)
        self.prompt_len = (int(prompt_len[0]), int(prompt_len[1]))
        self.max_new_tokens = int(max_new_tokens)
        self.deadline = deadline
        self.tenants = list(tenants) if tenants else []
        self.max_requests = None if max_requests is None \
            else int(max_requests)
        self.prefix = str(prefix)
        self._rng = np.random.RandomState(int(seed))
        self._credit = 0.0
        self._last = None
        self._ticks = 0
        self.storm = None          # (rps, tenant) once the rule fired
        self.submitted = []        # RoutedRequests accepted
        self.rejected = 0          # typed OverQuotaError refusals
        self.rejected_by_tenant = {}

    def _storm_check(self):
        from .. import resilience

        if self.storm is not None:
            return
        inj = resilience.fault_point()
        rule = inj.rule("traffic_storm")
        if rule is not None \
                and self._ticks >= int(rule.get("after", 0)) \
                and inj.should("traffic_storm"):
            self.storm = (int(rule.get("rps", 100)),
                          rule.get("tenant"))

    def tick(self, now):
        """Advance the arrival process to ``now``; returns the number
        of requests submitted this tick (accepted + refused)."""
        from .qos import OverQuotaError

        self._ticks += 1
        self._storm_check()
        if self._last is None:
            self._last = now
            return 0
        dt = max(0.0, now - self._last)
        self._last = now
        rate = self.rate
        storm_tenant = None
        if self.storm is not None:
            rate, storm_tenant = self.storm
        self._credit += rate * dt
        n = int(self._credit)
        self._credit -= n
        emitted = 0
        for _ in range(n):
            if self.max_requests is not None \
                    and self.total_offered() >= self.max_requests:
                break
            lo, hi = self.prompt_len
            plen = int(self._rng.randint(lo, hi + 1))
            prompt = [int(t) for t in
                      self._rng.randint(1, self.vocab, size=plen)]
            tenant = storm_tenant
            if tenant is None and self.tenants:
                tenant = self.tenants[self.total_offered()
                                      % len(self.tenants)]
            token = "%s-%d" % (self.prefix, self.total_offered())
            try:
                rr = self.router.submit(
                    prompt, max_new_tokens=self.max_new_tokens,
                    deadline=self.deadline, token=token, tenant=tenant)
            except OverQuotaError as e:
                self.rejected += 1
                key = e.tenant or "default"
                self.rejected_by_tenant[key] = \
                    self.rejected_by_tenant.get(key, 0) + 1
            else:
                self.submitted.append(rr)
            emitted += 1
        return emitted

    def total_offered(self):
        return len(self.submitted) + self.rejected
