"""Flight recorder & diagnostics — the black-box layer over the
fused/async stack.

PR 5 answered "what is happening" (metrics registry, phase spans, RPC
tracing, ``mxt_top``). This module answers the post-incident questions a
multi-host pod (PR 8) and a serving fleet (PR 7) actually raise: *why is
this replica wedged*, *where did HBM go*, and *how much wall-clock was
productive* — without a human attached, and without adding a single
device read to the hot path. Four parts:

1. **Flight recorder.** A bounded ring of structured events — step
   dispatch/retire spans, RPC spans, membership epoch changes,
   reshard/checkpoint/eviction events — tapped straight off
   ``telemetry.emit_event`` (one tap feeds every existing event source;
   the sources did not have to change). :func:`dump_postmortem` writes
   ``mxt-postmortem-<ts>.json`` with the ring tail, every Python
   thread's stack, the engine's in-flight window state, the HBM ledger,
   the goodput ledger, a config snapshot, and a metrics snapshot — on
   fatal signal (SIGTERM/SIGABRT, plus ``faulthandler`` for hard
   crashes), on an unhandled exception (``sys.excepthook`` + the serve
   loop's catch), and on demand (``/debug/flightrecorder``).

2. **Hang watchdog.** Subsystems that make progress bump a *host
   counter* (:func:`progress`) and declare how much work is outstanding
   (:func:`register_source` / :func:`pending_scope`): engine window
   retires, KVStore RPC completions, membership heartbeats, the serving
   decode loop. A daemon thread (:class:`Watchdog`) watches ONLY those
   counters — never a device value — and when a source with outstanding
   work stops moving for ``MXT_WATCHDOG_TIMEOUT`` seconds it dumps
   thread stacks + window state + the recorder tail, bumps
   ``mxt_watchdog_stalls_total{source}``, and per
   ``MXT_WATCHDOG_ACTION=report|abort`` keeps reporting or exits with
   :data:`WATCHDOG_EXIT_CODE` so ``tools/launch.py --respawn`` (or the
   membership reaper) turns today's silent ``worker_freeze`` hang into
   a typed, diagnosable, respawnable death. ``check(now=...)`` takes an
   explicit clock so tests never sleep.

3. **HBM ledger.** Allocation sites register device bytes per pool —
   ``params``, ``optimizer``, ``kv_cache``, ``inflight_window``,
   ``prefetch`` — via :func:`hbm_set`/:func:`hbm_release` (pure host
   arithmetic on shape metadata; ``.nbytes`` never touches the device).
   Exported as ``mxt_hbm_bytes{pool}`` gauges with
   ``mxt_hbm_peak_bytes{pool}`` watermarks, reconciled against
   ``device.memory_stats()`` where the backend provides it
   (:func:`reconcile`), and snapshotted into every post-mortem.
   :func:`reraise_if_oom` catches ``RESOURCE_EXHAUSTED`` at the
   step/decode dispatch sites and re-raises annotated with the ledger —
   an OOM names the pool that ate the HBM instead of a bare XLA error.

4. **Goodput ledger + on-demand profiler.** Lost wall-clock is
   accounted by cause — ``compile``, ``checkpoint``, ``reshard``,
   ``stall``, ``data_wait`` — into ``mxt_lost_seconds_total{cause}``
   and ``mxt_goodput_ratio`` (productive fraction of elapsed time).
   ``/debug/trace?ms=N`` runs a programmatic ``jax.profiler`` capture
   and returns the trace archive, so the staged TPU runbook can pull
   per-program time/fusion attribution (PAPERS.md arXiv 2301.13062)
   from a live replica remotely; ``/debug/stacks``, ``/debug/memory``
   and ``/debug/flightrecorder`` ride the same telemetry endpoint.

Everything here observes host state the subsystems already maintain;
``tools/check_host_syncs.py`` scans this module, and the one deliberate
sync (draining the window inside the OOM post-mortem, where the hot
path is already dead) is ``sync-ok``-annotated.
"""
from __future__ import annotations

import collections
import contextlib
import json
import os
import sys
import threading
import time
import traceback

from .base import MXNetError

__all__ = [
    "FlightRecorder", "recorder", "record_event",
    "Watchdog", "watchdog", "enable", "disable", "enabled",
    "progress", "register_source", "unregister_source", "pending_scope",
    "progress_counts", "WATCHDOG_EXIT_CODE",
    "HBMLedger", "ledger", "hbm_set", "hbm_release", "reconcile",
    "reraise_if_oom",
    "record_lost", "goodput_snapshot", "reset_goodput",
    "dump_postmortem", "maybe_postmortem", "install_handlers",
    "thread_stacks", "capture_trace", "handle_debug",
]

# 128 + SIGABRT: the typed watchdog death. tools/launch.py --respawn
# recognizes it and logs the restart as a watchdog abort.
WATCHDOG_EXIT_CODE = 134


def _config():
    from . import config

    return config


def _telemetry():
    from . import telemetry

    return telemetry


# --------------------------------------------------------------------------
# flight recorder
# --------------------------------------------------------------------------
class FlightRecorder:
    """Bounded ring of structured events (newest last). Appends are a
    deque push under one lock — cheap enough to ride every telemetry
    event including the per-step spans."""

    def __init__(self, size=None):
        if size is None:
            size = int(_config().get("MXT_FLIGHT_RECORDER_SIZE"))
        if size < 1:
            raise MXNetError("flight recorder needs at least one slot")
        self.size = size
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=size)
        self.recorded = 0  # total ever recorded (ring may have dropped)

    def record(self, kind, **fields):
        row = {"ts": round(time.time(), 6), "kind": str(kind)}
        row.update(fields)
        self.record_row(row)
        return row

    def record_row(self, row):
        """Append one pre-built event row (the telemetry tap's entry)."""
        with self._lock:
            self._ring.append(row)
            self.recorded += 1

    def events(self, last=None):
        """The ring contents, oldest first (``last`` trims to the tail)."""
        with self._lock:
            out = list(self._ring)
        return out if last is None else out[-int(last):]

    def clear(self):
        with self._lock:
            self._ring.clear()

    def __len__(self):
        with self._lock:
            return len(self._ring)


_state_lock = threading.Lock()
_recorder = None
_tap_installed = False


def recorder():
    """The process flight recorder (created + tapped into telemetry on
    first use; ``mxnet_tpu`` imports this module so it is always live)."""
    global _recorder, _tap_installed
    if _recorder is None:
        with _state_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    if not _tap_installed:
        with _state_lock:
            if not _tap_installed:
                _telemetry().add_event_tap(_event_tap)
                _tap_installed = True
    return _recorder


def record_event(kind, **fields):
    """One structured flight-recorder event (also forwarded to the
    telemetry JSONL sink when one is active)."""
    _telemetry().emit_event(kind, **fields)  # the tap lands it in the ring


def _event_tap(row):
    """telemetry.emit_event tap: every event row — spans, RPC spans,
    membership/reshard/checkpoint events — lands in the ring; a few
    kinds also feed the goodput ledger."""
    rec = _recorder
    if rec is not None:
        rec.record_row(row)
    kind = row.get("kind")
    if kind == "span" and row.get("name") == "data_wait":
        _add_lost("data_wait", row.get("seconds") or 0.0)
    elif kind == "compile":
        _add_lost("compile", row.get("seconds") or 0.0)


# --------------------------------------------------------------------------
# progress sources (what the watchdog observes)
# --------------------------------------------------------------------------
_progress = {}        # source -> monotone host counter
_pending_fns = {}     # source -> callable() -> outstanding work (or None)
_pending_counts = collections.defaultdict(int)  # pending_scope bookkeeping


def progress(name):
    """Bump a source's progress heartbeat. Called from hot paths (engine
    retires, RPC completions, decode ticks) — one dict write, no lock:
    a racy lost increment still moves the counter, which is all the
    watchdog compares."""
    _progress[name] = _progress.get(name, 0) + 1


def register_source(name, pending_fn=None):
    """Declare a watchdog-observed source. ``pending_fn`` returns how
    much work is outstanding (0/None = idle, never stalled); it must be
    pure host bookkeeping — the watchdog calls it off-thread."""
    _progress.setdefault(name, 0)
    _pending_fns[name] = pending_fn


def unregister_source(name):
    _pending_fns.pop(name, None)
    _progress.pop(name, None)


@contextlib.contextmanager
def pending_scope(name):
    """Mark one unit of outstanding work for ``name`` (auto-registers
    the source over the scope counter): a blocked RPC inside the scope
    shows pending > 0 with a frozen counter — exactly a stall."""
    if name not in _pending_fns:
        register_source(
            name, pending_fn=lambda n=name: _pending_counts[n])
    _pending_counts[name] += 1
    try:
        yield
    finally:
        _pending_counts[name] -= 1


def progress_counts():
    """{source: (counter, pending)} — the watchdog's whole world view
    (also what post-mortems snapshot)."""
    out = {}
    for name, fn in list(_pending_fns.items()):
        try:
            pend = fn() if fn is not None else None
        except Exception:  # noqa: BLE001 — a dying source must not lie
            pend = None
        out[name] = (_progress.get(name, 0), pend)
    return out


# --------------------------------------------------------------------------
# hang watchdog
# --------------------------------------------------------------------------
def thread_stacks():
    """{thread name (id): [stack lines]} for every live Python thread —
    the stall report's core payload."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    return {
        "%s (%d)" % (names.get(ident, "?"), ident):
            [ln.rstrip("\n") for ln in traceback.format_stack(frame)]
        for ident, frame in frames.items()}


def _window_states():
    from . import engine

    try:
        return engine.window_states()
    except Exception:  # noqa: BLE001 — diagnostics never takes a process down
        return []


class Watchdog:
    """Daemon-thread hang detector over the progress sources.

    A source stalls when it has outstanding work (``pending_fn() > 0``)
    and its progress counter has not moved for ``timeout`` seconds. The
    check reads host counters only — by construction it can never add a
    device sync, and a wedged device shows up as frozen *retire*
    counters with a non-empty window. ``clock`` is injectable and
    :meth:`check` takes an explicit ``now`` so tests drive stall
    detection with a fake clock, zero sleeps."""

    def __init__(self, timeout=None, action=None, interval=None,
                 clock=time.monotonic, dump=True):
        cfg = _config()
        if timeout is None:
            timeout = cfg.get("MXT_WATCHDOG_TIMEOUT")
        if timeout is None or float(timeout) <= 0:  # sync-ok: host config scalar
            raise MXNetError(
                "Watchdog needs a positive timeout (pass one or set "
                "MXT_WATCHDOG_TIMEOUT)")
        self.timeout = float(timeout)  # sync-ok: host config scalar
        self.action = str(action or cfg.get("MXT_WATCHDOG_ACTION")).lower()
        if self.action not in ("report", "abort"):
            raise MXNetError("MXT_WATCHDOG_ACTION must be 'report' or "
                             "'abort', got %r" % self.action)
        if interval is None:
            interval = cfg.get("MXT_WATCHDOG_INTERVAL")
        if interval is None:
            interval = max(0.05, self.timeout / 4.0)
        self.interval = float(interval)  # sync-ok: host config scalar
        self._clock = clock
        self._dump = dump
        self._seen = {}       # source -> (count, ts of last movement)
        self._reported = {}   # source -> ts of last stall report
        self._stall_accounted = set()  # sources already in the goodput ledger
        self._thread = None
        self._stop = threading.Event()
        self.stall_reports = []  # report dicts, newest last (tests read)

    # -- detection --------------------------------------------------------
    def check(self, now=None):
        """One watchdog pass; returns the sources found stalled (and
        reports each at most once per timeout window)."""
        now = self._clock() if now is None else now
        if _trace_lock.locked():
            # a profiler capture is a KNOWN global pause (tracing +
            # serialization stall every loop): re-arm instead of
            # reporting — in abort mode a stall here would kill a
            # healthy replica for being profiled
            for name, (count, _) in progress_counts().items():
                self._seen[name] = (count, now)
            return []
        stalled = []
        for name, (count, pend) in progress_counts().items():
            seen = self._seen.get(name)
            if seen is None or seen[0] != count:
                self._seen[name] = (count, now)
                continue
            if not pend:  # idle (or unknown-idle): nothing owed, re-arm
                self._seen[name] = (count, now)
                continue
            stalled_for = now - seen[1]
            if stalled_for < self.timeout:
                continue
            stalled.append(name)
            last = self._reported.get(name)
            if last is None or now - last >= self.timeout:
                self._reported[name] = now
                self._report(name, stalled_for, count, pend, now)
        return stalled

    def _report(self, source, stalled_for, count, pend, now):
        report = {
            "source": source, "stalled_for_s": round(stalled_for, 3),
            "progress_count": count, "pending": pend,
            "action": self.action,
            "threads": thread_stacks(),
            "windows": _window_states(),
            "flight_recorder_tail": recorder().events(last=64),
        }
        self.stall_reports.append(report)
        tel = _telemetry()
        tel.counter(
            "mxt_watchdog_stalls_total",
            "Hang-watchdog stall reports by progress source.",
            ("source",)).labels(source).inc()
        # first report charges the whole stall so far; repeat reports
        # charge only the window since the last one (no double count)
        record_lost("stall", stalled_for
                    if source not in self._stall_accounted
                    else self.timeout)
        self._stall_accounted.add(source)
        record_event("watchdog_stall", source=source,
                     stalled_for_s=round(stalled_for, 3),
                     pending=pend, action=self.action)
        sys.stderr.write(
            "\n=== mxt watchdog: source %r made no progress for %.1fs "
            "(pending=%s, action=%s) ===\n%s\n"
            % (source, stalled_for, pend, self.action,
               "\n".join("--- %s ---\n%s" % (t, "\n".join(stack))
                         for t, stack in report["threads"].items())))
        sys.stderr.flush()
        path = None
        if self._dump:
            try:
                path = dump_postmortem(reason="watchdog:%s" % source,
                                       extra={"stall": {
                                           k: v for k, v in report.items()
                                           if k != "threads"}})
                sys.stderr.write("mxt watchdog: post-mortem -> %s\n" % path)
                sys.stderr.flush()
            except Exception:  # noqa: BLE001 — report even if the dump fails
                pass
        if self.action == "abort":
            sys.stderr.write(
                "mxt watchdog: aborting (exit %d) so the launcher/"
                "membership reaper can respawn this worker\n"
                % WATCHDOG_EXIT_CODE)
            sys.stderr.flush()
            os._exit(WATCHDOG_EXIT_CODE)
        return report

    # -- the daemon thread ------------------------------------------------
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="mxt-watchdog")
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.check()
            except Exception:  # noqa: BLE001 — the watchdog must survive
                pass

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


_watchdog = None


def watchdog():
    """The running process watchdog, or None."""
    return _watchdog


def enable(timeout=None, action=None, interval=None, handlers=True):
    """Arm the diagnostics layer: flight recorder tap, post-mortem
    handlers (signals + excepthook), and — when a timeout is available —
    the watchdog daemon thread. Returns the watchdog (or None when no
    timeout is configured; recorder + handlers still arm)."""
    global _watchdog, _armed
    recorder()
    _armed = True
    if handlers:
        install_handlers()
    if _watchdog is None:
        try:
            _watchdog = Watchdog(timeout=timeout, action=action,
                                 interval=interval)
        except MXNetError:
            if timeout is not None:
                raise
            return None  # no MXT_WATCHDOG_TIMEOUT: recorder-only mode
        _watchdog.start()
    return _watchdog


def disable():
    """Disarm: stop the watchdog and detach the telemetry tap (the
    bench A/B's 'off' leg; handlers stay — uninstalling signal handlers
    mid-run is riskier than keeping them)."""
    global _watchdog, _tap_installed, _armed
    _armed = False
    if _watchdog is not None:
        _watchdog.stop()
        _watchdog = None
    if _tap_installed:
        _telemetry().remove_event_tap(_event_tap)
        _tap_installed = False


def enabled():
    return _armed


_armed = False


# --------------------------------------------------------------------------
# HBM ledger
# --------------------------------------------------------------------------
class HBMLedger:
    """Per-pool device-byte accounting. Allocation sites call
    :meth:`set`/:meth:`release` with byte counts they compute from shape
    metadata (``.nbytes`` — never a device read); totals and peak
    watermarks export as ``mxt_hbm_bytes{pool}`` /
    ``mxt_hbm_peak_bytes{pool}``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pools = {}   # pool -> {key: nbytes}
        self._peaks = {}   # pool -> peak total bytes
        self._bytes_g = None
        self._peak_g = None

    def _gauges(self):
        if self._bytes_g is None:
            tel = _telemetry()
            self._bytes_g = tel.gauge(
                "mxt_hbm_bytes",
                "Device bytes accounted per subsystem pool (params, "
                "optimizer, kv_cache, inflight_window, prefetch, "
                "hot_row_cache).",
                ("pool",))
            self._peak_g = tel.gauge(
                "mxt_hbm_peak_bytes",
                "Peak watermark of mxt_hbm_bytes per pool.", ("pool",))
        return self._bytes_g, self._peak_g

    def set(self, pool, key, nbytes):
        """Install/replace one named allocation in a pool (idempotent —
        re-registering a site replaces its old size)."""
        pool, key = str(pool), str(key)
        with self._lock:
            entries = self._pools.setdefault(pool, {})
            entries[key] = int(nbytes)
            total = sum(entries.values())
            peak = max(self._peaks.get(pool, 0), total)
            self._peaks[pool] = peak
        bg, pg = self._gauges()
        bg.labels(pool).set(total)
        pg.labels(pool).set(peak)
        return total

    def release(self, pool, key):
        """Drop one named allocation; returns the bytes released."""
        pool, key = str(pool), str(key)
        with self._lock:
            entries = self._pools.get(pool)
            if not entries:
                return 0
            freed = entries.pop(key, 0)
            total = sum(entries.values())
        bg, _ = self._gauges()
        bg.labels(pool).set(total)
        return freed

    def pool_bytes(self, pool):
        with self._lock:
            return sum(self._pools.get(str(pool), {}).values())

    def total_bytes(self):
        with self._lock:
            return sum(sum(e.values()) for e in self._pools.values())

    def snapshot(self):
        """{pool: {bytes, peak_bytes, entries}} — the post-mortem and
        /debug/memory payload."""
        with self._lock:
            return {
                pool: {"bytes": sum(entries.values()),
                       "peak_bytes": self._peaks.get(pool, 0),
                       "entries": dict(entries)}
                for pool, entries in sorted(self._pools.items())}

    def reconcile(self, tolerance=0.25):
        """Ledger total vs the backend's view. Where the device reports
        ``memory_stats()`` (TPU/GPU), ``delta_bytes`` is device minus
        ledger and ``within_tolerance`` flags drift beyond
        ``tolerance`` × device bytes (unaccounted allocations — a pool
        someone forgot to register). CPU backends report no stats;
        reconciliation then degrades to ledger-only (``delta_bytes``
        None, trivially within tolerance)."""
        ledger_total = self.total_bytes()
        device_bytes = None
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats()
            if stats:
                device_bytes = int(stats.get("bytes_in_use", 0)) or None
        except Exception:  # noqa: BLE001 — reconciliation is best-effort
            device_bytes = None
        out = {"ledger_bytes": ledger_total,
               "device_bytes_in_use": device_bytes,
               "delta_bytes": None, "within_tolerance": True}
        if device_bytes:
            out["delta_bytes"] = device_bytes - ledger_total
            out["within_tolerance"] = \
                abs(out["delta_bytes"]) <= tolerance * device_bytes
        return out


_ledger = None


def ledger():
    global _ledger
    if _ledger is None:
        with _state_lock:
            if _ledger is None:
                _ledger = HBMLedger()
    return _ledger


def hbm_set(pool, key, nbytes):
    return ledger().set(pool, key, nbytes)


def hbm_release(pool, key):
    return ledger().release(pool, key)


def reconcile(tolerance=0.25):
    return ledger().reconcile(tolerance)


def _is_oom(exc):
    msg = str(exc)
    return "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower()


def reraise_if_oom(exc, site):
    """Called from the step/decode dispatch ``except`` blocks: when the
    error is an allocation failure, re-raise it annotated with the HBM
    ledger snapshot (and leave a post-mortem); any other error returns
    so the caller re-raises the original."""
    if not _is_oom(exc):
        return
    from . import engine

    try:
        # the hot path is already dead; drain the window so the ledger
        # and in-flight state in the report describe a settled process
        engine.wait_all()  # sync-ok: OOM post-mortem drain (cold path)
    except Exception:  # noqa: BLE001 — the original OOM must still surface
        pass
    snap = ledger().snapshot()
    recon = reconcile()
    record_event("oom", site=str(site), error=str(exc)[:500],
                 hbm={p: v["bytes"] for p, v in snap.items()})
    path = None
    if _armed:
        try:
            path = dump_postmortem(reason="oom:%s" % site)
        except Exception:  # noqa: BLE001
            pass
    pools = ", ".join("%s=%d (peak %d)"
                      % (p, v["bytes"], v["peak_bytes"])
                      for p, v in snap.items()) or "<no pools registered>"
    raise MXNetError(
        "allocation failure at %s: %s\nHBM ledger: %s\n"
        "device bytes_in_use: %s%s"
        % (site, exc, pools, recon["device_bytes_in_use"],
           "\npost-mortem: %s" % path if path else "")) from exc


# --------------------------------------------------------------------------
# goodput ledger
# --------------------------------------------------------------------------
_goodput_lock = threading.Lock()
_lost = collections.defaultdict(float)  # cause -> seconds
_goodput_start = time.monotonic()
_lost_counter = None
_ratio_gauge = None


def _add_lost(cause, seconds):
    global _lost_counter
    seconds = float(seconds)  # sync-ok: host wall-clock scalar
    if seconds <= 0:
        return
    with _goodput_lock:
        _lost[str(cause)] += seconds
    if _lost_counter is None:
        _lost_counter = _telemetry().counter(
            "mxt_lost_seconds_total",
            "Wall-clock lost to non-productive causes (compile, "
            "checkpoint, reshard, stall, data_wait).", ("cause",))
    _lost_counter.labels(str(cause)).inc(seconds)


def record_lost(cause, seconds):
    """Account ``seconds`` of lost wall-clock to ``cause`` and refresh
    ``mxt_goodput_ratio``."""
    _add_lost(cause, seconds)
    goodput_snapshot()


def reset_goodput(start=None):
    """Zero the ledger (tests; a new epoch of accounting). ``start``
    overrides the productive-time epoch for fake-clock arithmetic."""
    global _goodput_start
    with _goodput_lock:
        _lost.clear()
        _goodput_start = time.monotonic() if start is None \
            else float(start)  # sync-ok: host clock scalar


def goodput_snapshot(now=None):
    """{elapsed_s, lost_s, lost_by_cause, goodput_ratio} — elapsed since
    the accounting epoch, lost summed by cause, ratio = productive /
    elapsed. Also publishes the ``mxt_goodput_ratio`` gauge."""
    global _ratio_gauge
    now = time.monotonic() if now is None else float(now)  # sync-ok: host clock
    with _goodput_lock:
        lost_by = dict(_lost)
        elapsed = max(0.0, now - _goodput_start)
    lost = sum(lost_by.values())
    ratio = 1.0 if elapsed <= 0 else max(0.0, (elapsed - lost) / elapsed)
    if _ratio_gauge is None:
        _ratio_gauge = _telemetry().gauge(
            "mxt_goodput_ratio",
            "Productive fraction of wall-clock since the accounting "
            "epoch (1 - lost/elapsed).")
    _ratio_gauge.set(round(ratio, 6))
    return {"elapsed_s": elapsed, "lost_s": lost,
            "lost_by_cause": lost_by, "goodput_ratio": ratio}


# --------------------------------------------------------------------------
# post-mortem
# --------------------------------------------------------------------------
def _config_snapshot():
    cfg = _config()
    out = {}
    for name in sorted(cfg.variables()):
        try:
            out[name] = cfg.get(name)
        except Exception:  # noqa: BLE001
            out[name] = "<unreadable>"
    return out


def dump_postmortem(reason="on_demand", extra=None, directory=None):
    """Write ``mxt-postmortem-<ts>.json`` (ring tail + thread stacks +
    window state + HBM ledger + goodput + config + metrics snapshot)
    into ``MXT_POSTMORTEM_DIR``; returns the path."""
    directory = directory or _config().get("MXT_POSTMORTEM_DIR") or "."
    os.makedirs(directory, exist_ok=True)
    ts = time.time()
    doc = {
        "reason": str(reason),
        "ts": round(ts, 6),
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "events": recorder().events(),
        "threads": thread_stacks(),
        "windows": _window_states(),
        "hbm": ledger().snapshot(),
        "hbm_reconcile": reconcile(),
        "goodput": goodput_snapshot(),
        "progress_sources": {k: {"count": c, "pending": p}
                             for k, (c, p) in progress_counts().items()},
        "config": _config_snapshot(),
    }
    try:
        doc["metrics"] = _telemetry().registry().snapshot_values()
    except Exception:  # noqa: BLE001 — a torn registry must not stop the dump
        doc["metrics"] = {}
    if extra is not None:
        doc["extra"] = extra
    name = "mxt-postmortem-%s-%d.json" % (
        time.strftime("%Y%m%d-%H%M%S", time.localtime(ts)),
        int((ts % 1) * 1e6))
    path = os.path.join(directory, name)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True, default=str)
        f.flush()
    os.replace(tmp, path)
    tel = _telemetry()
    tel.counter(
        "mxt_postmortems_total",
        "Post-mortem dumps by trigger.",
        ("trigger",)).labels(str(reason).split(":", 1)[0]).inc()
    return path


def maybe_postmortem(reason, extra=None):
    """Post-mortem only when the diagnostics layer is armed (so a bare
    library user's exception doesn't litter files); returns the path or
    None."""
    if not _armed:
        return None
    try:
        return dump_postmortem(reason=reason, extra=extra)
    except Exception:  # noqa: BLE001 — diagnostics never masks the real error
        return None


_handlers_installed = False
_prev_excepthook = None


def install_handlers():
    """Fatal-path capture: ``faulthandler`` for hard crashes, Python
    handlers for SIGTERM/SIGABRT (dump, then die with the conventional
    code), and a ``sys.excepthook`` wrapper for unhandled exceptions.
    Idempotent; main-thread only for the signal half."""
    global _handlers_installed, _prev_excepthook
    if _handlers_installed:
        return
    _handlers_installed = True
    import faulthandler
    import signal

    try:
        faulthandler.enable()
    except Exception:  # noqa: BLE001 — stderr may be closed under a harness
        pass

    def _sig_handler(signum, frame):
        del frame
        try:
            dump_postmortem(reason="signal:%d" % signum)
        except Exception:  # noqa: BLE001
            pass
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)

    if threading.current_thread() is threading.main_thread():
        for sig in (signal.SIGTERM, signal.SIGABRT):
            try:
                signal.signal(sig, _sig_handler)
            except (ValueError, OSError):
                pass

    prev = sys.excepthook

    def _excepthook(etype, value, tb):
        try:
            dump_postmortem(reason="unhandled:%s" % etype.__name__)
        except Exception:  # noqa: BLE001
            pass
        prev(etype, value, tb)

    _prev_excepthook = prev
    sys.excepthook = _excepthook


# --------------------------------------------------------------------------
# on-demand profiler capture
# --------------------------------------------------------------------------
_trace_lock = threading.Lock()


def capture_trace(ms=500, logdir=None):
    """Programmatic ``jax.profiler`` capture: trace for ``ms``
    milliseconds, then return ``(archive_path, archive_bytes)`` of the
    zipped trace directory — what ``/debug/trace?ms=N`` serves, so the
    TPU runbook can pull fusion/time attribution off a live replica.

    The whole capture (trace + serialization, which on a busy CPU
    fused loop can dwarf the window — keep ``ms`` small there) is
    accounted as ``profile`` lost time, and the watchdog suspends
    stall checks while it runs."""
    import shutil
    import tempfile

    import jax

    if not _trace_lock.acquire(blocking=False):
        raise MXNetError("a profiler capture is already in progress")
    try:
        # bound the window: tracing a busy jit loop emits events FAST
        # (a 100 ms capture of the CPU fused-step loop is ~10s of MB)
        ms = min(max(0.0, float(ms)), 60_000.0)  # sync-ok: host scalar
        _t0 = time.monotonic()
        workdir = logdir or tempfile.mkdtemp(prefix="mxt-trace-")
        jax.profiler.start_trace(workdir)
        try:
            time.sleep(ms / 1e3)  # sync-ok: requested capture window
        finally:
            jax.profiler.stop_trace()
        archive = shutil.make_archive(workdir, "zip", workdir)
        with open(archive, "rb") as f:
            data = f.read()
        if logdir is None:
            # transient capture: nothing may linger in the tempdir —
            # the archive BYTES are the product
            shutil.rmtree(workdir, ignore_errors=True)
            try:
                os.remove(archive)
            except OSError:
                pass
        record_event("profiler_capture", ms=ms,
                     archive_bytes=len(data))
        record_lost("profile", time.monotonic() - _t0)
        return archive, data
    finally:
        _trace_lock.release()


# --------------------------------------------------------------------------
# /debug/* routes (dispatched by telemetry's HTTP endpoint)
# --------------------------------------------------------------------------
def handle_debug(path, query=""):
    """(status, content_type, body_bytes) for one /debug/* request."""
    from urllib.parse import parse_qs

    params = {k: v[-1] for k, v in parse_qs(query).items()}
    if path == "/debug/stacks":
        body = "\n".join(
            "--- %s ---\n%s" % (name, "\n".join(stack))
            for name, stack in sorted(thread_stacks().items()))
        return 200, "text/plain; charset=utf-8", body.encode("utf-8")
    if path == "/debug/memory":
        doc = {"hbm": ledger().snapshot(), "reconcile": reconcile(),
               "goodput": goodput_snapshot()}
        return (200, "application/json",
                json.dumps(doc, indent=1, default=str).encode("utf-8"))
    if path == "/debug/flightrecorder":
        doc = {"size": recorder().size, "recorded": recorder().recorded,
               "events": recorder().events(),
               "windows": _window_states(),
               "progress_sources": {
                   k: {"count": c, "pending": p}
                   for k, (c, p) in progress_counts().items()}}
        return (200, "application/json",
                json.dumps(doc, indent=1, default=str).encode("utf-8"))
    if path == "/debug/postmortem":
        try:
            out = dump_postmortem(reason="debug_route")
        except Exception as e:  # noqa: BLE001 — report, don't crash the server
            return (500, "text/plain; charset=utf-8",
                    ("postmortem failed: %s" % e).encode("utf-8"))
        return (200, "application/json",
                json.dumps({"path": out}).encode("utf-8"))
    if path == "/debug/trace":
        try:
            ms = float(params.get("ms", 500))  # sync-ok: query param
            _, data = capture_trace(ms=ms)
        except Exception as e:  # noqa: BLE001 — busy/unsupported backends
            return (503, "text/plain; charset=utf-8",
                    ("trace capture failed: %s" % e).encode("utf-8"))
        return 200, "application/zip", data
    if path == "/debug/timeline":
        # distributed request traces as Chrome trace-event JSON
        # (Perfetto-loadable): one trace via ?trace_id=, or the whole
        # fleet's span log. Served by the fleet collector when one is
        # registered; a bare replica serves its own spans.
        from . import telemetry_fleet

        return telemetry_fleet.handle_timeline(params)
    return 404, "text/plain; charset=utf-8", b"unknown debug route"


def _maybe_autostart():
    """Arm the recorder tap at package import; start the watchdog (and
    the fatal-path handlers) when MXT_WATCHDOG_TIMEOUT is set."""
    try:
        recorder()
        if _config().get("MXT_WATCHDOG_TIMEOUT") is not None:
            enable()
    except Exception:  # noqa: BLE001 — observability must never block import
        pass
