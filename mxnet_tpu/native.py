"""ctypes bridge to the native RecordIO engine (mxnet_tpu/src/recordio.cc).

The reference keeps its data plane in C++ (dmlc-core recordio +
src/io/iter_image_recordio_2.cc worker threads); this module is that
layer for the TPU build. The shared library is compiled on first use with
the system g++ (no pybind11 in this image — plain C ABI + ctypes) and
cached next to the source. Everything degrades gracefully: if no
compiler/toolchain is available, ``available()`` returns False and the
pure-Python paths in recordio.py / io/io.py keep working.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

__all__ = ["available", "NativeRecordReader", "NativePrefetcher",
           "select_payload_by_starts"]

_HEADER_BYTES = 8  # [magic u32][cflag|len u32] precede every payload


def select_payload_by_starts(offsets, lengths, wanted_starts):
    """Map .idx sidecar offsets (record starts) onto a native scan's
    (payload offsets, lengths), preserving the sidecar's order/subset.
    Returns (offsets, lengths) or None when any start is unknown (stale
    sidecar — callers fall back to the Python reader, whose first read
    surfaces the clear invalid-magic error)."""
    by_start = {int(o) - _HEADER_BYTES: i for i, o in enumerate(offsets)}
    try:
        sel = [by_start[int(w)] for w in wanted_starts]
    except KeyError:
        return None
    return offsets[sel], lengths[sel]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "recordio.cc")
_SO = os.path.join(_HERE, "src", "libmxt_recordio.so")

_lib = None
_lib_lock = threading.Lock()
_build_err = None


def _build():
    # compile to a per-process temp name, then atomically rename: N
    # launcher-spawned processes may race to build the same cache path,
    # and a sibling must never CDLL a half-written .so
    tmp = "%s.%d.tmp" % (_SO, os.getpid())
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-pthread", _SRC, "-o", tmp]
    res = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    if res.returncode != 0:
        raise RuntimeError("native build failed: %s" % res.stderr[-500:])
    os.replace(tmp, _SO)


def _load():
    global _lib, _build_err
    if _lib is not None or _build_err is not None:
        return _lib
    with _lib_lock:
        if _lib is not None or _build_err is not None:
            return _lib
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                _build()
            lib = ctypes.CDLL(_SO)
        except Exception as e:  # noqa: BLE001 — no toolchain, bad cache, ...
            _build_err = e
            return None
        c = ctypes
        lib.mxt_rio_open.restype = c.c_void_p
        lib.mxt_rio_open.argtypes = [c.c_char_p]
        lib.mxt_rio_close.argtypes = [c.c_void_p]
        lib.mxt_rio_file_size.restype = c.c_int64
        lib.mxt_rio_file_size.argtypes = [c.c_void_p]
        lib.mxt_rio_scan.restype = c.c_int64
        lib.mxt_rio_scan.argtypes = [c.c_void_p, c.POINTER(c.c_int64),
                                     c.POINTER(c.c_int64), c.c_int64]
        lib.mxt_rio_read.restype = c.c_int64
        lib.mxt_rio_read.argtypes = [c.c_void_p, c.c_int64, c.c_int64,
                                     c.POINTER(c.c_uint8)]
        lib.mxt_rio_read_next.restype = c.c_int64
        lib.mxt_rio_read_next.argtypes = [c.c_void_p, c.POINTER(c.c_uint8),
                                          c.c_int64, c.POINTER(c.c_int64)]
        lib.mxt_rio_prefetch_start.restype = c.c_void_p
        lib.mxt_rio_prefetch_start.argtypes = [
            c.c_char_p, c.POINTER(c.c_int64), c.POINTER(c.c_int64),
            c.POINTER(c.c_int64), c.c_int64, c.c_int32, c.c_int32]
        lib.mxt_rio_prefetch_pop.restype = c.c_int64
        lib.mxt_rio_prefetch_pop.argtypes = [c.c_void_p,
                                             c.POINTER(c.c_uint8),
                                             c.c_int64,
                                             c.POINTER(c.c_int64)]
        lib.mxt_rio_prefetch_stop.argtypes = [c.c_void_p]
        _lib = lib
        return _lib


def available():
    """True when the native engine compiled + loaded on this machine."""
    return _load() is not None


def _as_i64_ptr(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


class NativeRecordReader:
    """Random/sequential access over one RecordIO shard, native-parsed."""

    def __init__(self, path):
        lib = _load()
        if lib is None:
            raise RuntimeError("native recordio unavailable: %r"
                               % (_build_err,))
        self._lib = lib
        self._h = lib.mxt_rio_open(path.encode())
        if not self._h:
            raise IOError("cannot open %s" % path)
        self.path = path
        self._offsets = None
        self._lengths = None

    def close(self):
        if self._h:
            self._lib.mxt_rio_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    def scan(self):
        """Index the shard by magic-walk; returns (offsets, lengths)."""
        if self._offsets is not None:
            return self._offsets, self._lengths
        # first pass with cap=0 counts records exactly — sizing the buffer
        # from file_size would allocate GBs for big shards and silently
        # truncate shards full of zero-length records
        empty = np.empty(0, np.int64)
        n = self._lib.mxt_rio_scan(self._h, _as_i64_ptr(empty),
                                   _as_i64_ptr(empty), 0)
        if n < 0:
            raise RuntimeError("corrupt RecordIO framing in %s" % self.path)
        offs = np.empty(n, np.int64)
        lens = np.empty(n, np.int64)
        n2 = self._lib.mxt_rio_scan(self._h, _as_i64_ptr(offs),
                                    _as_i64_ptr(lens), n)
        if n2 != n:
            raise RuntimeError("shard %s changed during scan" % self.path)
        self._offsets = offs
        self._lengths = lens
        return self._offsets, self._lengths

    def __len__(self):
        return len(self.scan()[0])

    def read(self, i):
        """Payload bytes of record i (by shard position)."""
        offs, lens = self.scan()
        return self.read_at(int(offs[i]), int(lens[i]))

    def read_at(self, offset, length):
        """Payload bytes at a known (offset, length) — no scan needed."""
        buf = np.empty(length, np.uint8)
        got = self._lib.mxt_rio_read(
            self._h, offset, length,
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        if got != length:
            raise IOError("short read in %s" % self.path)
        return buf.tobytes()

    def read_next(self):
        """Next record in file order, or None at EOF."""
        needed = ctypes.c_int64(0)
        cap = 1 << 16
        while True:
            buf = np.empty(cap, np.uint8)
            got = self._lib.mxt_rio_read_next(
                self._h, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                cap, ctypes.byref(needed))
            if got == 0:
                return None
            if got > 0:
                return buf[:got].tobytes()
            if needed.value > cap:  # retry with the exact size
                cap = int(needed.value)
                continue
            raise RuntimeError("corrupt RecordIO framing in %s" % self.path)


class NativePrefetcher:
    """Threaded read-ahead over a shard in a caller-given record order.

    Workers parse + copy records into a bounded ring off the GIL; ``pop``
    returns payloads strictly in the requested order. This is the
    reference's PrefetcherIter/worker-pool role for the raw-bytes stage.
    """

    def __init__(self, path, offsets, lengths, order, num_threads=4,
                 capacity=64):
        lib = _load()
        if lib is None:
            raise RuntimeError("native recordio unavailable: %r"
                               % (_build_err,))
        self._lib = lib
        self.path = path
        offsets = np.ascontiguousarray(offsets, np.int64)
        lengths = np.ascontiguousarray(lengths, np.int64)
        order = np.ascontiguousarray(order, np.int64)
        self._n = len(order)
        self._max_len = int(lengths[order].max()) if self._n else 0
        self._h = lib.mxt_rio_prefetch_start(
            path.encode(), _as_i64_ptr(offsets), _as_i64_ptr(lengths),
            _as_i64_ptr(order), self._n, int(num_threads), int(capacity))
        if not self._h:
            raise RuntimeError("prefetcher failed to start")

    def pop(self):
        """Next payload in order, or None when exhausted."""
        if self._h is None:
            return None
        needed = ctypes.c_int64(0)
        buf = np.empty(max(self._max_len, 1), np.uint8)
        got = self._lib.mxt_rio_prefetch_pop(
            self._h, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            buf.size, ctypes.byref(needed))
        if got == 0:
            return None
        if got == -2:
            raise IOError("prefetch worker IO failure on %s (shard "
                          "truncated or deleted mid-epoch?)" % self.path)
        if got < 0:
            raise RuntimeError("prefetch pop: buffer too small (%d < %d)"
                               % (buf.size, needed.value))
        return buf[:got].tobytes()

    def stop(self):
        if self._h is not None:
            self._lib.mxt_rio_prefetch_stop(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    def __iter__(self):
        while True:
            b = self.pop()
            if b is None:
                return
            yield b
