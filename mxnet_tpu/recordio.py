"""RecordIO — framed binary record files
(ref: 3rdparty/dmlc-core/include/dmlc/recordio.h,
python/mxnet/recordio.py — MXRecordIO/MXIndexedRecordIO/IRHeader/pack/unpack).

Byte format follows the dmlc spec: every record is
``[kMagic u32][cflag:3|len:29 u32][payload][pad to 4B]`` so shards are
recoverable by magic-scan and readable by dmlc tooling. Image records carry
an IRHeader prefix (flag, label, id, id2) with optional multi-label tail.
Pure python implementation (the reference's C++ reader is a host-side
throughput concern; the TPU build overlaps decode with device compute in the
iterator layer instead — see io/).
"""
from __future__ import annotations

import io as _io
import numbers
import os
import struct
from collections import namedtuple

import numpy as np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_KMAGIC = 0xCED7230A
_LEN_MASK = (1 << 29) - 1


class MXRecordIO:
    """Sequential RecordIO reader/writer (ref: recordio.py — MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.is_open = False
        self.open()

    def open(self):
        if self.flag == "w":
            self.handle = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.handle = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.is_open = True

    def close(self):
        if self.is_open:
            self.handle.close()
            self.is_open = False

    def __del__(self):
        self.close()

    def __getstate__(self):
        """Support pickling across process workers — how the data
        plane's decode fleet would receive shard handles. The reference
        (recordio.py — __getstate__) CLOSED the live handle because it
        held a C pointer; a Python file handle just needs excluding, so
        pickling an OPEN reader no longer kills the parent's handle (a
        parent that ships a reader to N workers keeps reading). An open
        writer is flushed first so the clone observes its bytes; note a
        writer clone reopens with truncating "w", reference semantics."""
        if self.is_open and self.writable:
            self.handle.flush()
        d = dict(self.__dict__)
        del d["handle"]
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.handle = None
        is_open = d["is_open"]
        self.is_open = False
        if is_open:
            self.open()

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        return self.handle.tell()

    def write(self, buf):
        assert self.writable
        header = struct.pack("<II", _KMAGIC, len(buf) & _LEN_MASK)
        self.handle.write(header)
        self.handle.write(buf)
        pad = (4 - (len(buf) % 4)) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        header = self.handle.read(8)
        if len(header) < 8:
            return None
        magic, lrec = struct.unpack("<II", header)
        if magic != _KMAGIC:
            raise RuntimeError(
                "invalid RecordIO magic 0x%08x at offset %d"
                % (magic, self.handle.tell() - 8))
        length = lrec & _LEN_MASK
        buf = self.handle.read(length)
        pad = (4 - (length % 4)) % 4
        if pad:
            self.handle.read(pad)
        return buf


class MXIndexedRecordIO(MXRecordIO):
    """RecordIO with a sidecar .idx for random seek
    (ref: recordio.py — MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.flag == "r" and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    if len(parts) < 2:
                        continue
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)
        if self.flag == "w":
            self.fidx = open(self.idx_path, "w")

    def close(self):
        if self.fidx is not None:
            self.fidx.close()
            self.fidx = None
        super().close()

    def __getstate__(self):
        if self.fidx is not None:
            self.fidx.flush()
        d = super().__getstate__()
        d.pop("fidx", None)
        return d

    def __setstate__(self, d):
        d = dict(d)
        d["fidx"] = None
        super().__setstate__(d)

    def seek(self, idx):
        assert not self.writable
        self.handle.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        assert self.writable
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


# --------------------------------------------------------------------------
# image record header (ref: recordio.py — IRHeader/pack/unpack)
# --------------------------------------------------------------------------
IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack an IRHeader + payload into bytes (ref: recordio.py — pack).
    Multi-label: header.label is an array → flag stores its length."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        header = header._replace(label=float(header.label))
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0)
        s = label.tobytes() + s
    return struct.pack(_IR_FORMAT, *header) + s


def unpack(s):
    """Unpack bytes into (IRHeader, payload) (ref: recordio.py — unpack)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[: header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode an image array and pack it (ref: recordio.py — pack_img;
    OpenCV imencode → PIL here)."""
    from PIL import Image

    arr = np.asarray(img).astype(np.uint8)
    buf = _io.BytesIO()
    fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
    Image.fromarray(arr).save(buf, format=fmt, quality=quality)
    return pack(header, buf.getvalue())


def unpack_img(s, iscolor=1):
    """Unpack and decode an image record (ref: recordio.py — unpack_img).
    Returns (IRHeader, HxWx3 uint8 array)."""
    from PIL import Image

    header, img_bytes = unpack(s)
    img = Image.open(_io.BytesIO(img_bytes))
    if iscolor:
        img = img.convert("RGB")
    else:
        img = img.convert("L")
    return header, np.asarray(img)
