"""Evaluation metrics (ref: python/mxnet/metric.py).

Same registry + API: ``create(name)``, ``EvalMetric.update(labels, preds)``,
``get() -> (name, value)``, ``CompositeEvalMetric``, custom fn via
``np()``/``CustomMetric``.

The reference's ``metric.update`` calls ``asnumpy()`` — a full
device→host round-trip per batch that stalls the async dispatch engine
(engine.py). The common metrics (Accuracy, Loss, MAE, MSE/RMSE) therefore
accumulate ON DEVICE when fed NDArrays: the per-batch statistic stays a
jax scalar added into a running device sum, and ``get()`` performs the
ONE host read (through the deferred-handle protocol, ndarray/pending.py).
numpy inputs keep the host path, and metrics without a device
implementation fall back to it unchanged.
"""
from __future__ import annotations

import math
from collections import OrderedDict

import numpy
import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray
from .ndarray.pending import PendingValue

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss", "Torch",
           "Caffe", "CustomMetric", "np", "create", "register"]

_REGISTRY = {}


def register(klass, *names):
    for n in names or (klass.__name__.lower(),):
        _REGISTRY[n.lower()] = klass
    return klass


def create(metric, *args, **kwargs):
    """Create a metric from name / callable / list (ref: metric.py — create)."""
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    if isinstance(metric, str):
        if metric.lower() not in _REGISTRY:
            raise MXNetError("metric %r is not registered" % (metric,))
        return _REGISTRY[metric.lower()](*args, **kwargs)
    raise TypeError("metric must be a name, callable, EvalMetric, or list")


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()  # sync-ok: host-path metrics funnel (per batch)
    return _np.asarray(x)  # sync-ok: numpy input, no device transfer


def _jnp():
    import jax.numpy as jnp

    return jnp


def check_label_shapes(labels, preds, shape=False):
    if not shape:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError(
            "Shape of labels {} does not match shape of predictions {}"
            .format(label_shape, pred_shape))


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        # device-side accumulator: running jax-scalar sum (instance counts
        # are static and stay host-side); ONE host read at get()
        self._dev_sum = None
        self._dev_inst = 0
        self.reset()

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))

    def get_config(self):
        config = self._kwargs.copy()
        config.update({
            "metric": self.__class__.__name__,
            "name": self.name,
            "output_names": self.output_names,
            "label_names": self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names if name in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names if name in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self._dev_sum = None
        self._dev_inst = 0

    # -- device-side accumulation (async engine support) -----------------
    def _accum_device(self, value, n):
        """Add one batch's statistic without a host read: ``value`` is a
        jax scalar, ``n`` the (static) instance count it covers."""
        self._dev_sum = value if self._dev_sum is None \
            else self._dev_sum + value
        self._dev_inst += n

    def _drain_device(self):
        """Fold the device accumulator into the host totals — the ONE
        deferred read, at get() time."""
        if self._dev_sum is not None:
            self.sum_metric += float(PendingValue(self._dev_sum))
            self.num_inst += self._dev_inst
            self._dev_sum = None
            self._dev_inst = 0

    def get(self):
        self._drain_device()
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


@register
class CompositeEvalMetric(EvalMetric):
    """Manage multiple metrics as one (ref: metric.py — CompositeEvalMetric)."""

    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        try:
            return self.metrics[index]
        except IndexError:
            return ValueError("Metric index {} is out of range 0 and {}"
                              .format(index, len(self.metrics)))

    def update_dict(self, labels, preds):
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        try:
            for metric in self.metrics:
                metric.reset()
        except AttributeError:
            pass

    def get(self):
        names = []
        values = []
        for metric in self.metrics:
            name, value = metric.get()
            if isinstance(name, str):
                name = [name]
            if isinstance(value, (float, int, _np.generic)):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, axis=axis)
        self.axis = axis

    def update(self, labels, preds):
        if isinstance(labels, (NDArray, _np.ndarray)):
            labels = [labels]
        if isinstance(preds, (NDArray, _np.ndarray)):
            preds = [preds]
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            if isinstance(label, NDArray) and isinstance(pred_label,
                                                         NDArray):
                # device path: correct-count stays a jax scalar, no host
                # read until get() (same int math as the host path)
                jnp = _jnp()
                ld, pd = label.data, pred_label.data
                if pd.shape != ld.shape:
                    pd = jnp.argmax(pd, axis=self.axis)
                correct = (pd.astype(jnp.int32).ravel() ==
                           ld.astype(jnp.int32).ravel()).sum()
                self._accum_device(correct, int(_np.prod(ld.shape)) or 1)
                continue
            pred_label = _as_np(pred_label)
            label = _as_np(label)
            if pred_label.shape != label.shape:
                pred_label = _np.argmax(pred_label, axis=self.axis)
            pred_label = pred_label.astype("int32").flat
            label = label.astype("int32").flat
            num_correct = int((_np.asarray(pred_label) ==
                               _np.asarray(label)).sum())
            self.sum_metric += num_correct
            self.num_inst += len(_np.asarray(label))


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, top_k=top_k)
        self.top_k = top_k
        assert self.top_k > 1, "Please use Accuracy if top_k is no more than 1"
        self.name += "_%d" % self.top_k

    def update(self, labels, preds):
        if isinstance(labels, (NDArray, _np.ndarray)):
            labels = [labels]
        if isinstance(preds, (NDArray, _np.ndarray)):
            preds = [preds]
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            assert len(pred_label.shape) == 2, \
                "Predictions should be 2 dims with first dim as batch"
            pred_label = _np.argsort(_as_np(pred_label).astype("float32"),
                                    axis=1)
            label = _as_np(label).astype("int32")
            num_samples = pred_label.shape[0]
            num_dims = len(pred_label.shape)
            if num_dims == 1:
                self.sum_metric += (pred_label.flat == label.flat).sum()
            elif num_dims == 2:
                num_classes = pred_label.shape[1]
                top_k = min(num_classes, self.top_k)
                for j in range(top_k):
                    self.sum_metric += (
                        pred_label[:, num_classes - 1 - j].flat ==
                        label.flat).sum()
            self.num_inst += num_samples


@register
class F1(EvalMetric):
    """Binary F1 (ref: metric.py — F1; average='macro'|'micro')."""

    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        self._tp = self._fp = self._fn = 0.0
        self._sum_f1 = 0.0
        self._count = 0
        super().__init__(name, output_names, label_names)

    def reset(self):
        self._tp = self._fp = self._fn = 0.0
        self._sum_f1 = 0.0
        self._count = 0
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        if isinstance(labels, (NDArray, _np.ndarray)):
            labels = [labels]
        if isinstance(preds, (NDArray, _np.ndarray)):
            preds = [preds]
        for label, pred in zip(labels, preds):
            pred = _as_np(pred)
            label = _as_np(label).astype("int32")
            pred_label = _np.argmax(pred, axis=1) if pred.ndim > 1 else \
                (pred > 0.5).astype("int32")
            if not _np.all(_np.isin(label, [0, 1])):
                raise ValueError("F1 currently only supports binary classification.")
            tp = float(((pred_label == 1) & (label == 1)).sum())
            fp = float(((pred_label == 1) & (label == 0)).sum())
            fn = float(((pred_label == 0) & (label == 1)).sum())
            if self.average == "micro":
                self._tp += tp
                self._fp += fp
                self._fn += fn
            else:
                prec = tp / (tp + fp) if tp + fp > 0 else 0.0
                rec = tp / (tp + fn) if tp + fn > 0 else 0.0
                f1 = 2 * prec * rec / (prec + rec) if prec + rec > 0 else 0.0
                self._sum_f1 += f1
                self._count += 1
            self.num_inst += label.size

    def get(self):
        if self.average == "micro":
            prec = self._tp / (self._tp + self._fp) \
                if self._tp + self._fp > 0 else 0.0
            rec = self._tp / (self._tp + self._fn) \
                if self._tp + self._fn > 0 else 0.0
            f1 = 2 * prec * rec / (prec + rec) if prec + rec > 0 else 0.0
            return (self.name, f1 if self.num_inst > 0 else float("nan"))
        if self._count == 0:
            return (self.name, float("nan"))
        return (self.name, self._sum_f1 / self._count)


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient (ref: metric.py — MCC)."""

    def __init__(self, name="mcc", output_names=None, label_names=None):
        self._tp = self._fp = self._tn = self._fn = 0.0
        super().__init__(name, output_names, label_names)

    def reset(self):
        self._tp = self._fp = self._tn = self._fn = 0.0
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        if isinstance(labels, (NDArray, _np.ndarray)):
            labels = [labels]
        if isinstance(preds, (NDArray, _np.ndarray)):
            preds = [preds]
        for label, pred in zip(labels, preds):
            pred = _as_np(pred)
            label = _as_np(label).astype("int32")
            pred_label = _np.argmax(pred, axis=1) if pred.ndim > 1 else \
                (pred > 0.5).astype("int32")
            self._tp += float(((pred_label == 1) & (label == 1)).sum())
            self._fp += float(((pred_label == 1) & (label == 0)).sum())
            self._tn += float(((pred_label == 0) & (label == 0)).sum())
            self._fn += float(((pred_label == 0) & (label == 1)).sum())
            self.num_inst += label.size

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        terms = [(self._tp + self._fp), (self._tp + self._fn),
                 (self._tn + self._fp), (self._tn + self._fn)]
        denom = 1.0
        for t in terms:
            denom *= t if t != 0 else 1.0
        mcc = (self._tp * self._tn - self._fp * self._fn) / math.sqrt(denom)
        return (self.name, mcc)


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         ignore_label=ignore_label, axis=axis)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        if isinstance(labels, (NDArray, _np.ndarray)):
            labels = [labels]
        if isinstance(preds, (NDArray, _np.ndarray)):
            preds = [preds]
        assert len(labels) == len(preds)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label = _as_np(label).astype("int32").reshape(-1)
            pred = _as_np(pred)
            pred = pred.reshape(-1, pred.shape[-1])
            probs = pred[_np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = _np.where(ignore, 1.0, probs)
                num -= int(ignore.sum())
            loss -= float(_np.sum(_np.log(_np.maximum(1e-10, probs))))
            num += label.shape[0]
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        if isinstance(labels, (NDArray, _np.ndarray)):
            labels = [labels]
        if isinstance(preds, (NDArray, _np.ndarray)):
            preds = [preds]
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            if isinstance(label, NDArray) and isinstance(pred, NDArray):
                ld, pd = label.data, pred.data
                if ld.ndim == 1:
                    ld = ld.reshape(-1, 1)
                if pd.ndim == 1:
                    pd = pd.reshape(-1, 1)
                self._accum_device(_jnp().abs(ld - pd).mean(), 1)
                continue
            label = _as_np(label)
            pred = _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += float(_np.abs(label - pred).mean())
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        if isinstance(labels, (NDArray, _np.ndarray)):
            labels = [labels]
        if isinstance(preds, (NDArray, _np.ndarray)):
            preds = [preds]
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            if isinstance(label, NDArray) and isinstance(pred, NDArray):
                ld, pd = label.data, pred.data
                if ld.ndim == 1:
                    ld = ld.reshape(-1, 1)
                if pd.ndim == 1:
                    pd = pd.reshape(-1, 1)
                self._accum_device(((ld - pd) ** 2.0).mean(), 1)
                continue
            label = _as_np(label)
            pred = _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += float(((label - pred) ** 2.0).mean())
            self.num_inst += 1


@register
class RMSE(MSE):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def get(self):
        self._drain_device()
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.sqrt(self.sum_metric / self.num_inst))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        if isinstance(labels, (NDArray, _np.ndarray)):
            labels = [labels]
        if isinstance(preds, (NDArray, _np.ndarray)):
            preds = [preds]
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label).ravel()
            pred = _as_np(pred)
            assert label.shape[0] == pred.shape[0]
            prob = pred[_np.arange(label.shape[0]), _np.int64(label)]
            self.sum_metric += float((-_np.log(prob + self.eps)).sum())
            self.num_inst += label.shape[0]


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps=eps, name=name, output_names=output_names,
                         label_names=label_names)


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        if isinstance(labels, (NDArray, _np.ndarray)):
            labels = [labels]
        if isinstance(preds, (NDArray, _np.ndarray)):
            preds = [preds]
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label)
            pred = _as_np(pred)
            check_label_shapes(label, pred, shape=True)
            self.sum_metric += float(
                _np.corrcoef(pred.ravel(), label.ravel())[0, 1])
            self.num_inst += 1


@register
class Loss(EvalMetric):
    """Average of a loss output (ref: metric.py — Loss)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        if isinstance(preds, (NDArray, _np.ndarray)):
            preds = [preds]
        for pred in preds:
            if isinstance(pred, NDArray):
                # device path: per-batch sum stays a jax scalar
                self._accum_device(pred.data.sum(), pred.size)
                continue
            pred = _as_np(pred)
            self.sum_metric += float(pred.sum())
            self.num_inst += pred.size


@register
class Torch(Loss):
    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name, output_names, label_names, feval=feval,
                         allow_extra_outputs=allow_extra_outputs)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if isinstance(labels, (NDArray, _np.ndarray)):
            labels = [labels]
        if isinstance(preds, (NDArray, _np.ndarray)):
            preds = [preds]
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for pred, label in zip(preds, labels):
            label = _as_np(label)
            pred = _as_np(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


# reference registry aliases (ref: metric.py @register(...) names)
register(Accuracy, "acc", "accuracy")
register(TopKAccuracy, "top_k_accuracy", "top_k_acc")
register(CrossEntropy, "ce", "cross-entropy")
register(NegativeLogLikelihood, "nll_loss")
register(PearsonCorrelation, "pearsonr")
register(CompositeEvalMetric, "composite")


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy-taking function into a metric
    (ref: metric.py — np())."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)
