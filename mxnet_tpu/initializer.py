"""Weight initializers (ref: python/mxnet/initializer.py).

Registry + the reference zoo: Zero/One/Constant/Uniform/Normal/Orthogonal/
Xavier/MSRAPrelu/Bilinear/LSTMBias, plus `mixed` pattern dispatch via
InitDesc names.
"""
from __future__ import annotations

import math
import re

import jax.numpy as jnp
import numpy as np

from .base import MXNetError


def _rng():
    """Draws ride the framework PRNG so mx.random.seed() reproduces inits
    (ref: initializer.py draws via the global MXNet RNG, seeded by
    mx.random.seed)."""
    from . import random as _random
    import jax

    seed_arr = jax.random.key_data(_random.new_key())
    return np.random.default_rng(np.asarray(seed_arr).astype(np.uint32))

__all__ = ["Initializer", "register", "create", "Zero", "One", "Constant",
           "Uniform", "Normal", "Orthogonal", "Xavier", "MSRAPrelu",
           "Bilinear", "LSTMBias", "Mixed", "InitDesc"]

_REGISTRY = {}


_ALIAS = {"zeros": "zero", "ones": "one"}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    if isinstance(name, str):
        key = name.lower()
        key = _ALIAS.get(key, key)
        if key not in _REGISTRY:
            raise MXNetError("unknown initializer %r" % (name,))
        return _REGISTRY[key](**kwargs)
    raise TypeError("cannot create initializer from %r" % (name,))


class InitDesc(str):
    """Parameter name + attrs hint used for pattern dispatch
    (ref: initializer.py — InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, name, arr):
        """Fill ``arr`` (NDArray) based on the parameter name, reproducing
        the reference's name-based dispatch (weight/bias/gamma/beta/...).
        A parameter-specific initializer carried in InitDesc attrs wins over
        suffix dispatch (ref: initializer.py — the '__init__' attr bypass)."""
        if isinstance(name, InitDesc) and name.attrs.get("__init__"):
            create(name.attrs["__init__"])._init_weight(name, arr)
            return
        if not isinstance(name, str):
            name = ""
        if name.endswith("weight"):
            self._init_weight(name, arr)
        elif name.endswith("bias"):
            self._init_zero(name, arr)
        elif name.endswith("gamma"):
            self._init_one(name, arr)
        elif name.endswith("beta"):
            self._init_zero(name, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(name, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(name, arr)
        else:
            self._init_weight(name, arr)

    init_weight = __call__

    def _fill(self, arr, np_value):
        arr._set_data(jnp.asarray(np_value, dtype=arr.dtype))

    def _init_zero(self, name, arr):
        self._fill(arr, np.zeros(arr.shape))

    def _init_one(self, name, arr):
        self._fill(arr, np.ones(arr.shape))

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def __repr__(self):
        return "%s(%s)" % (type(self).__name__, self._kwargs)


@register
class Zero(Initializer):
    def _init_weight(self, name, arr):
        self._init_zero(name, arr)


@register
class One(Initializer):
    def _init_weight(self, name, arr):
        self._init_one(name, arr)


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        self._fill(arr, np.full(arr.shape, self.value))


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        self._fill(arr, _rng().uniform(-self.scale, self.scale, arr.shape))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        self._fill(arr, _rng().normal(0.0, self.sigma, arr.shape))


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _rng().uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = _rng().normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        self._fill(arr, self.scale * q.reshape(arr.shape))


@register
class Xavier(Initializer):
    """ref: initializer.py — Xavier(rnd_type, factor_type, magnitude)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise MXNetError(
                "Xavier initializer needs >=2D shape, got %s for %r"
                % (shape, str(name)))
        if len(shape) > 2:
            hw_scale = float(np.prod(shape[2:]))
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError("invalid factor_type %r" % (self.factor_type,))
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            self._fill(arr, _rng().uniform(-scale, scale, shape))
        elif self.rnd_type == "gaussian":
            self._fill(arr, _rng().normal(0, scale, shape))
        else:
            raise MXNetError("invalid rnd_type %r" % (self.rnd_type,))


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        shape = arr.shape
        weight = np.zeros(int(np.prod(shape)), dtype=np.float64)
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._fill(arr, weight.reshape(shape))


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (ref: initializer.py — LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = np.zeros(arr.shape)
        num_hidden = arr.shape[0] // 4
        b[num_hidden:2 * num_hidden] = self.forget_bias
        self._fill(arr, b)


class Mixed(Initializer):
    def __init__(self, patterns, initializers):
        super().__init__()
        self.map = [(re.compile(p), init) for p, init in
                    zip(patterns, initializers)]

    def __call__(self, name, arr):
        for pat, init in self.map:
            if pat.match(str(name)):
                init(name, arr)
                return
        raise MXNetError("no initializer pattern matches %r" % (str(name),))
