"""HybridBlock → Symbol export (ref: gluon/block.py — HybridBlock.export;
the reference traces the CachedOp graph to symbol.json + params).

Because ``mx.sym`` mirrors ``mx.nd`` over one registry, exporting is just
re-running hybrid_forward with Symbol inputs: the same layer code that
computed arrays now composes a graph.
"""
from __future__ import annotations

from ..base import MXNetError
from ..ndarray import ndarray as _nd

__all__ = ["export_block"]


def export_block(block, path, epoch=0):
    """Write path-symbol.json + path-%04d.params (arg:/aux: keyed)."""
    from .. import autograd as ag
    from . import var as _var
    from ..model import save_checkpoint

    params = block.collect_params()
    for p in params.values():
        if p._data is None:
            raise MXNetError(
                "export: parameter %s is not initialized; run a forward "
                "pass first" % p.name)

    data = _var("data")
    with ag.pause(train_mode=False):
        out = block(data)
    if isinstance(out, (list, tuple)):
        from . import Group

        out = Group(list(out))

    aux_names = set(out.list_auxiliary_states())
    arg_params = {}
    aux_params = {}
    for name, p in params.items():
        if name in aux_names:
            aux_params[name] = p.data()
        else:
            arg_params[name] = p.data()
    save_checkpoint(path, epoch, out, arg_params, aux_params)
    return "%s-symbol.json" % path, "%s-%04d.params" % (path, epoch)
