"""``mx.sym`` — the symbolic namespace.

Generated from the SAME op registry as ``mx.nd`` (ref:
python/mxnet/symbol/register.py — _init_op_module; SURVEY invariant "one op
registry serves both execution modes"): every registered op becomes a
symbol-composing function here and an eager function there.
"""
from __future__ import annotations

import sys as _sys

from .. import attribute as _attribute
from .. import name as _naming
from ..base import MXNetError
from ..ops import registry as _registry
from .symbol import (
    Symbol, Variable, var, Group, load, load_json, _Node,
    OP_INPUTS, VISIBLE_OUTPUTS, num_outputs_for,
)

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json"]


def _apply_sym_op(op_name, *args, name=None, attr=None, **kwargs):
    """Compose a graph node (ref: nnvm Symbol::Compose). Missing trailing
    inputs of table ops become auto-created Variables named
    <node-name>_<input-name>."""
    op = _registry.get_op(op_name)
    inputs = []
    for a in args:
        if a is None:
            inputs.append(None)
        elif isinstance(a, Symbol):
            if len(a) != 1:
                raise MXNetError(
                    "op %s: cannot take a multi-output symbol as one input"
                    % op.name)
            inputs.append(a._outputs[0])
        else:
            raise TypeError(
                "op %s: positional inputs must be Symbols, got %r"
                % (op.name, type(a)))

    # the active NameManager resolves (name, hint) — a Prefix manager
    # prefixes both generated and explicit names (ref: name.py)
    node_name = _naming.current().get(name, op.name.lower().lstrip("_"))
    # scope/attr= entries are pure annotations, resolved up front so the
    # auto-created variable inputs below inherit them too (the reference
    # attaches AttrScope attrs to every symbol created in scope)
    annotations = _attribute.current().get(attr)

    info = OP_INPUTS.get(op.name)
    if info is not None:
        in_names = info["inputs"]
        # pull Symbol kwargs by input name (mx.sym.FC(data=..., weight=...))
        for i, nm in enumerate(in_names):
            if nm in kwargs and isinstance(kwargs[nm], Symbol):
                sym_in = kwargs.pop(nm)
                while len(inputs) <= i:
                    inputs.append(None)
                inputs[i] = sym_in._outputs[0]
        n_expected = len(in_names)
        if op.name in ("FullyConnected", "Convolution", "Deconvolution") \
                and kwargs.get("no_bias", False):
            n_expected -= 1
        if op.name == "RNN" and kwargs.get("mode", "lstm") != "lstm":
            n_expected -= 1  # no state_cell
        while len(inputs) < n_expected:
            inputs.append(None)
        for i in range(len(inputs)):
            if inputs[i] is None:
                vname = "%s_%s" % (node_name, in_names[i])
                inputs[i] = _Node(None, vname, {}, [],
                                  annotations=dict(annotations)), 0
    else:
        # Symbol kwargs not in a table op: treat as named extra inputs is
        # unsupported — require positional
        for k, v in list(kwargs.items()):
            if isinstance(v, Symbol):
                raise MXNetError(
                    "op %s: pass array input %r positionally" % (op.name, k))
        while inputs and inputs[-1] is None:
            inputs.pop()  # trailing None = optional input left at default
        if any(i is None for i in inputs):
            raise MXNetError(
                "op %s: non-trailing None input not allowed (no "
                "auto-variable table entry)" % op.name)

    # op kwargs are execution params — kept apart from annotations so an
    # annotation named like a fn param (e.g. AttrScope(p=...) around
    # Dropout) can't leak into execution
    attrs = {}
    for k, v in kwargs.items():
        if isinstance(v, list):
            v = tuple(v)
        attrs[k] = v
    n_out = num_outputs_for(op, kwargs)
    node = _Node(op.name, node_name, attrs, list(inputs),
                 num_outputs=n_out, annotations=annotations)
    n_vis = VISIBLE_OUTPUTS.get(op.name, n_out)
    return Symbol([(node, i) for i in range(n_vis)])


def _make_sym_func(op):
    def fn(*args, **kwargs):
        return _apply_sym_op(op.name, *args, **kwargs)

    fn.__name__ = op.name
    fn.__qualname__ = op.name
    fn.__doc__ = ((op.fn.__doc__ or "")
                  + "\n(symbolic form of registered op: %s)" % op.name)
    return fn


_mod = _sys.modules[__name__]
for _name in _registry.list_ops():
    _op = _registry.get_op(_name)
    if not hasattr(_mod, _name):
        setattr(_mod, _name, _make_sym_func(_op))
for _alias, _target in list(_registry._ALIASES.items()):
    if not hasattr(_mod, _alias):
        setattr(_mod, _alias, getattr(_mod, _target))

def zeros(shape=(), dtype="float32", name=None, **kwargs):
    """Constant-zeros symbol (ref: symbol creation API — mx.sym.zeros).
    ``shape`` must be fully known; rnn cells' default unroll state uses a
    shape-free zeros-from-inputs construction instead."""
    return _apply_sym_op("_zeros", shape=tuple(shape), dtype=dtype,
                         name=name, **kwargs)


def ones(shape=(), dtype="float32", name=None, **kwargs):
    """Constant-ones symbol (ref: mx.sym.ones)."""
    return _apply_sym_op("_ones", shape=tuple(shape), dtype=dtype,
                         name=name, **kwargs)


from .executor import Executor  # noqa: E402,F401


# ``mx.sym.contrib`` (ref: symbol/register.py — same prefix convention
# as the nd namespace)
from ..ndarray import _ContribNamespace as _CN  # noqa: E402

contrib = _CN(_mod)
