"""Symbol — the lazy graph IR (ref: nnvm Symbol/Graph,
python/mxnet/symbol/symbol.py; JSON format of nnvm pass SaveJSON).

The reference's Symbol composes nnvm nodes and executes via GraphExecutor.
Here a Symbol is a tiny DAG over the SAME op registry the imperative mode
dispatches (SURVEY invariant: one registry, two modes); binding lowers the
whole graph to one jitted XLA program (executor.py) — GraphExecutor's memory
planning, op fusion, and bulk execution all fall out of XLA compilation.
"""
from __future__ import annotations

import ast
import json

import numpy as np

from ..base import MXNetError

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json"]


# node naming lives in mxnet_tpu/name.py (NameManager/Prefix scopes)


class _Node:
    """One graph node: a variable (op=None) or an op application."""

    __slots__ = ("op", "name", "attrs", "inputs", "num_outputs",
                 "annotations")

    def __init__(self, op, name, attrs, inputs, num_outputs=1,
                 annotations=None):
        self.op = op  # None for variables, else registry op name (str)
        self.name = name
        self.attrs = attrs  # static params (python values)
        self.inputs = inputs  # list[(that _Node, int output_index)]
        self.num_outputs = num_outputs
        # user/AttrScope annotations (ctx_group, lr_mult, ...) — kept
        # OUT of attrs so they can never be mistaken for op parameters
        # at execution (the reference separates these the same way)
        self.annotations = annotations or {}

    def is_var(self):
        return self.op is None


# ops whose trailing array inputs are auto-created as Variables when not
# passed (ref: nnvm Symbol::Compose creates missing inputs named
# <op-name>_<input-name>); aux marks mutable state inputs
# (list_auxiliary_states)
OP_INPUTS = {
    "FullyConnected": {"inputs": ["data", "weight", "bias"], "aux": []},
    "Convolution": {"inputs": ["data", "weight", "bias"], "aux": []},
    "Deconvolution": {"inputs": ["data", "weight", "bias"], "aux": []},
    "BatchNorm": {"inputs": ["data", "gamma", "beta", "moving_mean",
                             "moving_var"],
                  "aux": ["moving_mean", "moving_var"]},
    "LayerNorm": {"inputs": ["data", "gamma", "beta"], "aux": []},
    "InstanceNorm": {"inputs": ["data", "gamma", "beta"], "aux": []},
    "GroupNorm": {"inputs": ["data", "gamma", "beta"], "aux": []},
    "Embedding": {"inputs": ["data", "weight"], "aux": []},
    "RNN": {"inputs": ["data", "parameters", "state", "state_cell"],
            "aux": []},
    "SoftmaxOutput": {"inputs": ["data", "label"], "aux": []},
    "LinearRegressionOutput": {"inputs": ["data", "label"], "aux": []},
    "MAERegressionOutput": {"inputs": ["data", "label"], "aux": []},
    "LogisticRegressionOutput": {"inputs": ["data", "label"], "aux": []},
}

LOSS_OPS = frozenset([
    "SoftmaxOutput", "LinearRegressionOutput", "MAERegressionOutput",
    "LogisticRegressionOutput", "MakeLoss", "softmax_cross_entropy",
])

# ops with hidden extra outputs (ref: nnvm FNumVisibleOutputs — BatchNorm's
# saved mean/var outputs exist at runtime but don't compose)
VISIBLE_OUTPUTS = {"BatchNorm": 1}


def num_outputs_for(op, attrs):
    """Per-call output arity — some ops vary by params (shared by compose
    and JSON load so the arity survives a save/load roundtrip)."""
    name = op.name
    if name in ("split", "SliceChannel"):
        return int(attrs.get("num_outputs", 1))
    if name == "split_v2":
        ios = attrs.get("indices_or_sections", 1)
        return ios if isinstance(ios, int) else len(tuple(ios)) + 1
    if name == "RNN":
        return 3 if attrs.get("mode", "lstm") == "lstm" else 2
    if name == "_sample_multinomial":
        return 2 if attrs.get("get_prob", False) else 1
    if name == "Proposal":
        return 2 if attrs.get("output_score", False) else 1
    if name == "amp_multicast":
        # reference amp_multicast requires num_outputs (= input count)
        return int(attrs.get("num_outputs", 1))
    return op.num_outputs


class Symbol:
    """A set of output entries over the node DAG
    (ref: symbol.py — Symbol; multi-output via Group/slicing)."""

    __slots__ = ("_outputs",)

    def __init__(self, outputs):
        self._outputs = list(outputs)  # list[(_Node, int)]

    # -- identity ------------------------------------------------------
    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def __repr__(self):
        return "<Symbol %s>" % (self.name or "Grouped",)

    def __len__(self):
        return len(self._outputs)

    def __iter__(self):
        return (Symbol([e]) for e in self._outputs)

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index in names:
                return Symbol([self._outputs[names.index(index)]])
            raise MXNetError("Cannot find output %r in %s" % (index, names))
        if isinstance(index, slice):
            return Symbol(self._outputs[index])
        node, oidx = self._outputs[0] if len(self._outputs) == 1 \
            else (None, None)
        if node is not None and node.num_outputs > 1 and len(self) == 1:
            # single node with multiple outputs: index selects one
            if index >= node.num_outputs:
                raise MXNetError("Index %d out of range" % index)
            return Symbol([(node, index)])
        return Symbol([self._outputs[index]])

    # -- graph walks ---------------------------------------------------
    def _topo_nodes(self):
        """Topological order of all reachable nodes (inputs first)."""
        seen = {}
        order = []

        def visit(node):
            if id(node) in seen:
                return
            seen[id(node)] = node
            for inp, _ in node.inputs:
                visit(inp)
            order.append(node)

        for node, _ in self._outputs:
            visit(node)
        return order

    def _aux_names_set(self):
        aux = set()
        for node in self._topo_nodes():
            if node.is_var() or node.op not in OP_INPUTS:
                continue
            names = OP_INPUTS[node.op]["inputs"]
            auxes = OP_INPUTS[node.op]["aux"]
            for (inp, _), nm in zip(node.inputs, names):
                if inp.is_var() and nm in auxes:
                    aux.add(inp.name)
        return aux

    def list_arguments(self):
        aux = self._aux_names_set()
        return [n.name for n in self._topo_nodes()
                if n.is_var() and n.name not in aux]

    def list_auxiliary_states(self):
        aux = self._aux_names_set()
        return [n.name for n in self._topo_nodes()
                if n.is_var() and n.name in aux]

    def list_outputs(self):
        out = []
        for node, oidx in self._outputs:
            n_vis = VISIBLE_OUTPUTS.get(node.op, node.num_outputs)
            if n_vis > 1:
                out.append("%s_output%d" % (node.name, oidx))
            else:
                out.append("%s_output" % node.name)
        return out

    def list_inputs(self):
        return [n.name for n in self._topo_nodes() if n.is_var()]

    def get_internals(self):
        """Symbol whose outputs are ALL node outputs
        (ref: symbol.py — get_internals)."""
        outs = []
        for node in self._topo_nodes():
            for i in range(node.num_outputs):
                outs.append((node, i))
        return Symbol(outs)

    def get_children(self):
        node = self._outputs[0][0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    @property
    def attr_dict(self):
        out = {}
        for node in self._topo_nodes():
            merged = {k: str(v) for k, v in node.attrs.items()
                      if not k.startswith("__")}
            merged.update(
                {k: str(v) for k, v in node.annotations.items()})
            if merged:
                out[node.name] = merged
        return out

    def attr(self, key):
        node = self._outputs[0][0]
        v = node.annotations.get(key)
        if v is None:
            v = node.attrs.get(key)
        return str(v) if v is not None else None

    # -- shape / dtype inference --------------------------------------
    def infer_shape(self, *args, **kwargs):
        arg_shapes, out_shapes, aux_shapes = self.infer_shape_partial(
            *args, **kwargs)
        if any(s is None or 0 in s for s in arg_shapes):
            unknown = [n for n, s in zip(self.list_arguments(), arg_shapes)
                       if s is None or 0 in s]
            raise MXNetError(
                "infer_shape: cannot fully infer shapes for arguments %s; "
                "provide their shapes" % (unknown,))
        return arg_shapes, out_shapes, aux_shapes

    def infer_shape_partial(self, *args, **kwargs):
        """Forward shape propagation (ref: nnvm pass InferShape). Known data
        shapes flow forward; parameter-input shapes are deduced per-op
        (PARAM_SHAPE_RULES), everything else via jax.eval_shape on the
        registered op fn."""
        if args:
            names = self.list_arguments()
            for n, s in zip(names, args):
                if s is not None:
                    kwargs[n] = s
        return _infer_shapes(self, kwargs)

    def infer_type(self, **kwargs):
        """Forward dtype propagation (ref: nnvm pass InferType). Known arg
        dtypes flow through the same walk as shape inference: where input
        shapes are known, jax.eval_shape gives the op's exact output dtype;
        where they are not, a ``dtype`` node attr (Cast, zeros, …) or
        numpy promotion of the input dtypes is used."""
        known_dt = {k: np.dtype(v) for k, v in kwargs.items()
                    if v is not None}
        _, _, _, arg_t, out_t, aux_t = _infer_shapes(
            self, {}, known_dtypes=known_dt, want_types=True)
        return arg_t, out_t, aux_t

    # -- serialization -------------------------------------------------
    def tojson(self):
        """nnvm-compatible JSON (ref: nnvm pass SaveJSON — the
        model-symbol.json format)."""
        nodes = self._topo_nodes()
        nid = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        arg_nodes = []
        for i, n in enumerate(nodes):
            if n.is_var():
                arg_nodes.append(i)
            jattrs = {k: str(v) for k, v in n.attrs.items()}
            if n.annotations:
                if n.is_var():
                    accepted = frozenset()
                else:
                    from ..ops.registry import fn_params, get_op

                    accepted = fn_params(get_op(n.op).fn) or frozenset()
                for k, v in n.annotations.items():
                    # an annotation matching ANY op parameter (passed or
                    # defaulted) must not deserialize as the execution
                    # value — park it under a reversible private key
                    key = k if k not in accepted else "__ann_%s__" % k
                    jattrs[key] = str(v)
            jnodes.append({
                "op": "null" if n.is_var() else n.op,
                "name": n.name,
                "attrs": jattrs,
                "inputs": [[nid[id(inp)], oi, 0] for inp, oi in n.inputs],
            })
        heads = [[nid[id(n)], oi, 0] for n, oi in self._outputs]
        return json.dumps({
            "nodes": jnodes,
            "arg_nodes": arg_nodes,
            "node_row_ptr": list(range(len(nodes) + 1)),
            "heads": heads,
            "attrs": {"mxnet_version": ["int", 10600]},
        }, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- execution -----------------------------------------------------
    def eval(self, ctx=None, **kwargs):
        """One-shot evaluation with NDArray args (ref: symbol.py — eval)."""
        ex = self.bind(ctx, kwargs)
        return ex.forward()

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None):
        from .executor import Executor

        return Executor(self, ctx, args or {}, args_grad, grad_req,
                        aux_states)

    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    **kwargs):
        from .executor import Executor

        return Executor.simple_bind(self, ctx, grad_req, type_dict, **kwargs)

    # -- arithmetic sugar (ref: symbol.py operator overloads) ----------
    def _binop(self, other, op_name, scalar_op=None, reverse=False):
        from . import _apply_sym_op

        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _apply_sym_op(op_name, a, b)
        if scalar_op is None:
            raise TypeError("unsupported operand: %r" % (other,))
        kw = {"scalar": float(other)}
        if reverse:
            kw["reverse"] = True
        return _apply_sym_op(scalar_op, self, **kw)

    def __add__(self, other):
        return self._binop(other, "broadcast_add", "_plus_scalar")

    def __radd__(self, other):
        return self.__add__(other)

    def __sub__(self, other):
        return self._binop(other, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, other):
        from . import _apply_sym_op

        if isinstance(other, Symbol):
            return other.__sub__(self)
        return _apply_sym_op("_rminus_scalar", self, scalar=float(other))

    def __mul__(self, other):
        return self._binop(other, "broadcast_mul", "_mul_scalar")

    def __rmul__(self, other):
        return self.__mul__(other)

    def __truediv__(self, other):
        return self._binop(other, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, other):
        from . import _apply_sym_op

        if isinstance(other, Symbol):
            return other.__truediv__(self)
        return _apply_sym_op("_rdiv_scalar", self, scalar=float(other))

    def __pow__(self, other):
        return self._binop(other, "broadcast_power", "_power_scalar")

    def __neg__(self):
        return self.__mul__(-1.0)

    def reshape(self, shape, **kwargs):
        from . import _apply_sym_op

        return _apply_sym_op("reshape", self, shape=tuple(shape), **kwargs)

    def __getattr__(self, name):
        # sym.exp(), sym.sum(axis=..) style method calls forward to ops
        if name.startswith("_"):
            raise AttributeError(name)
        from ..ops.registry import _OPS, _ALIASES

        if name in _OPS or name in _ALIASES:
            from . import _apply_sym_op

            def method(*args, **kw):
                return _apply_sym_op(name, self, *args, **kw)

            return method
        raise AttributeError("Symbol has no attribute %r" % name)


def var(name, attr=None, shape=None, dtype=None, init=None, stype=None,
        **kwargs):
    """Create a variable symbol (ref: symbol.py — var/Variable)."""
    del stype
    from .. import attribute as _attribute

    annotations = _attribute.current().get(attr)  # active AttrScope
    annotations.update(kwargs)
    attrs = {}
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        attrs["__dtype__"] = str(np.dtype(dtype))
    if init is not None:
        attrs["__init__"] = str(init)
    return Symbol([(_Node(None, name, attrs, [],
                          annotations=annotations), 0)])


Variable = var


def Group(symbols):
    """Group symbols into one multi-output symbol (ref: symbol.py — Group)."""
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def load_json(json_str):
    """Rebuild a Symbol from nnvm JSON (ref: nnvm pass LoadJSON)."""
    from ..ops.registry import get_op

    data = json.loads(json_str)
    nodes = []
    for jn in data["nodes"]:
        attrs = {}
        parked = {}  # __ann_<k>__ keys: annotations parked on collision
        for k, v in (jn.get("attrs") or jn.get("param") or {}).items():
            if k.startswith("__ann_") and k.endswith("__"):
                parked[k[len("__ann_"):-2]] = str(v)
            else:
                attrs[k] = _parse_attr(v)
        if jn["op"] == "null":
            # variables: only the __special__ keys are structural; the
            # rest are user annotations
            ann = {k: v for k, v in attrs.items()
                   if not k.startswith("__")}
            ann.update(parked)
            attrs = {k: v for k, v in attrs.items()
                     if k.startswith("__")}
            node = _Node(None, jn["name"], attrs, [], annotations=ann)
        else:
            op = get_op(jn["op"])  # raises if unknown
            # split params from annotations by the op fn's signature
            # (the serialized format stores them in one dict, like the
            # reference's JSON)
            from .executor import _fn_params

            accepted = _fn_params(op.fn)
            if accepted is not None:
                ann = {k: v for k, v in attrs.items()
                       if k not in accepted and not k.startswith("__")}
                attrs = {k: v for k, v in attrs.items()
                         if k in accepted or k.startswith("__")}
            else:
                ann = {}
            ann.update(parked)
            node = _Node(op.name, jn["name"], attrs, [],
                         num_outputs=num_outputs_for(op, attrs),
                         annotations=ann)
        nodes.append(node)
    for node, jn in zip(nodes, data["nodes"]):
        node.inputs = [(nodes[i[0]], i[1]) for i in jn["inputs"]]
    heads = data.get("heads") or [[len(nodes) - 1, 0, 0]]
    return Symbol([(nodes[h[0]], h[1]) for h in heads])


def _parse_attr(v):
    if not isinstance(v, str):
        return v
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


# ---------------------------------------------------------------------------
# shape inference
# ---------------------------------------------------------------------------
def _fc_param_shapes(data_shape, attrs, num_inputs):
    num_hidden = int(attrs["num_hidden"])
    flatten = attrs.get("flatten", True)
    in_units = int(np.prod(data_shape[1:])) if flatten else data_shape[-1]
    shapes = {"weight": (num_hidden, in_units)}
    if num_inputs > 2:
        shapes["bias"] = (num_hidden,)
    return shapes


def _conv_param_shapes(data_shape, attrs, num_inputs):
    num_filter = int(attrs["num_filter"])
    kernel = tuple(attrs["kernel"])
    groups = int(attrs.get("num_group", 1))
    shapes = {"weight": (num_filter, data_shape[1] // groups) + kernel}
    if num_inputs > 2 and not attrs.get("no_bias", False):
        shapes["bias"] = (num_filter,)
    return shapes


def _deconv_param_shapes(data_shape, attrs, num_inputs):
    num_filter = int(attrs["num_filter"])
    kernel = tuple(attrs["kernel"])
    shapes = {"weight": (data_shape[1], num_filter) + kernel}
    if num_inputs > 2 and not attrs.get("no_bias", False):
        shapes["bias"] = (num_filter,)
    return shapes


def _norm_param_shapes(data_shape, attrs, num_inputs):
    axis = int(attrs.get("axis", 1))
    c = data_shape[axis]
    return {"gamma": (c,), "beta": (c,), "moving_mean": (c,),
            "moving_var": (c,)}


def _embedding_param_shapes(data_shape, attrs, num_inputs):
    return {"weight": (int(attrs["input_dim"]), int(attrs["output_dim"]))}


def _rnn_param_shapes(data_shape, attrs, num_inputs):
    from ..ops.rnn import rnn_param_size

    mode = attrs.get("mode", "lstm")
    h = int(attrs["state_size"])
    L = int(attrs.get("num_layers", 1))
    bi = bool(attrs.get("bidirectional", False))
    d = 2 if bi else 1
    size = rnn_param_size(mode, data_shape[2], h, L, bi)
    shapes = {"parameters": (size,),
              "state": (L * d, data_shape[1], h)}
    if mode == "lstm":
        shapes["state_cell"] = (L * d, data_shape[1], h)
    return shapes


def _label_like_shapes(data_shape, attrs, num_inputs):
    if attrs.get("multi_output", False):
        return {"label": (data_shape[0],) + tuple(data_shape[2:])}
    return {"label": tuple(data_shape[:-1])}


def _reg_label_shapes(data_shape, attrs, num_inputs):
    return {"label": tuple(data_shape)}


PARAM_SHAPE_RULES = {
    "FullyConnected": _fc_param_shapes,
    "Convolution": _conv_param_shapes,
    "Deconvolution": _deconv_param_shapes,
    "BatchNorm": _norm_param_shapes,
    "LayerNorm": _norm_param_shapes,
    "InstanceNorm": _norm_param_shapes,
    "GroupNorm": _norm_param_shapes,
    "Embedding": _embedding_param_shapes,
    "RNN": _rnn_param_shapes,
    "SoftmaxOutput": _label_like_shapes,
    "LinearRegressionOutput": _reg_label_shapes,
    "MAERegressionOutput": _reg_label_shapes,
    "LogisticRegressionOutput": _reg_label_shapes,
}


def _unify_types(sym, known_dtypes):
    """Bidirectional dtype unification (ref: nnvm InferType's ElemwiseType
    unification). Forward: explicit ``dtype`` attrs and promotion of known
    input dtypes; backward: a var with no declared dtype (e.g. an FC
    weight) takes the dtype its consumer settled on, so
    ``infer_type(data=float16)`` makes the whole layer float16 instead of
    promoting against a float32 default. Unknowns stay None."""
    node_dt = {}
    topo = sym._topo_nodes()
    for node in topo:
        if node.is_var():
            dt = known_dtypes.get(node.name)
            if dt is None:
                declared = node.attrs.get("__dtype__")
                dt = np.dtype(declared) if declared is not None else None
            node_dt[(id(node), 0)] = dt
            continue
        if "dtype" in node.attrs:
            dt = np.dtype(node.attrs["dtype"])
        else:
            ins = [node_dt.get((id(inp), oi)) for inp, oi in node.inputs]
            ins = [d for d in ins if d is not None]
            dt = None
            if ins:
                try:
                    # jnp.promote_types, not np.result_type: numpy raises
                    # DTypePromotionError for bfloat16 vs float16/int
                    import jax.numpy as jnp
                    dt = ins[0]
                    for d in ins[1:]:
                        dt = np.dtype(jnp.promote_types(dt, d))
                except Exception:  # noqa: BLE001 — exotic pair: unknown
                    dt = None
        for i in range(node.num_outputs):
            node_dt[(id(node), i)] = dt
    for node in reversed(topo):
        if node.is_var() or "dtype" in node.attrs:
            continue  # Cast-like ops don't constrain their input dtype
        dt = node_dt.get((id(node), 0))
        if dt is None:
            continue
        for inp, oi in node.inputs:
            if node_dt.get((id(inp), oi)) is None:
                node_dt[(id(inp), oi)] = dt
    return node_dt


def _infer_shapes(sym, known, known_dtypes=None, want_types=False):
    """Returns (arg_shapes, out_shapes, aux_shapes) in list_* order; None
    for unknowable entries. With ``want_types`` also returns
    (arg_types, out_types, aux_types): output dtypes come from
    jax.eval_shape where input shapes are known, otherwise from a
    ``dtype`` node attr or numpy promotion of the input dtypes."""
    import jax
    import jax.numpy as jnp

    from ..ops.registry import get_op
    from .executor import _call_op_with_attrs

    known_dtypes = known_dtypes or {}
    shapes = {}  # id(node),oidx -> shape
    dtypes = {}
    var_shape = dict(known)
    pre_dt = _unify_types(sym, known_dtypes)

    def _dtype_of(inp, oi):
        return dtypes.get((id(inp), oi),
                          pre_dt.get((id(inp), oi)) or np.dtype("float32"))

    for node in sym._topo_nodes():
        if node.is_var():
            dtypes[(id(node), 0)] = _dtype_of(node, 0)
            s = var_shape.get(node.name, node.attrs.get("__shape__"))
            if s is not None and 0 not in tuple(s):
                shapes[(id(node), 0)] = tuple(s)
            continue
        in_shapes = []
        missing = []
        names = OP_INPUTS.get(node.op, {}).get("inputs")
        for i, (inp, oi) in enumerate(node.inputs):
            s = shapes.get((id(inp), oi))
            in_shapes.append(s)
            if s is None:
                missing.append(i)
        if missing and node.op in PARAM_SHAPE_RULES and \
                in_shapes[0] is not None:
            rule = PARAM_SHAPE_RULES[node.op]
            deduced = rule(in_shapes[0], node.attrs, len(node.inputs))
            for i in list(missing):
                inp, oi = node.inputs[i]
                nm = names[i] if names and i < len(names) else None
                if inp.is_var() and nm in deduced:
                    s = deduced[nm]
                    shapes[(id(inp), oi)] = s
                    in_shapes[i] = s
                    missing.remove(i)
        if missing:
            # shapes unknowable — dtypes still flow via the unification
            # pre-pass (explicit dtype attr, else promotion of inputs)
            for i in range(node.num_outputs):
                dtypes[(id(node), i)] = \
                    pre_dt.get((id(node), i)) or np.dtype("float32")
            continue  # cannot infer this node's output shapes
        op = get_op(node.op)
        structs = [
            jax.ShapeDtypeStruct(s, _dtype_of(inp, oi))
            for s, (inp, oi) in zip(in_shapes, node.inputs)]
        try:
            out = jax.eval_shape(
                lambda *xs: _call_op_with_attrs(op, node.attrs, False, xs),
                *structs)
        except Exception as e:  # noqa: BLE001
            raise MXNetError(
                "shape inference failed at op %s(%s): %s"
                % (node.op, node.name, e)) from e
        outs = out if isinstance(out, tuple) else (out,)
        for i, o in enumerate(outs):
            shapes[(id(node), i)] = tuple(o.shape)
            dtypes[(id(node), i)] = np.dtype(o.dtype)

    aux = sym._aux_names_set()
    node_by_name = {n.name: n for n in sym._topo_nodes() if n.is_var()}
    arg_shapes = [shapes.get((id(node_by_name[a]), 0))
                  for a in sym.list_arguments()]
    aux_shapes = [shapes.get((id(node_by_name[a]), 0))
                  for a in sym.list_auxiliary_states()]
    out_shapes = [shapes.get((id(n), oi)) for n, oi in sym._outputs]
    del jnp, aux
    if not want_types:
        return arg_shapes, out_shapes, aux_shapes
    arg_types = [_dtype_of(node_by_name[a], 0) for a in sym.list_arguments()]
    aux_types = [_dtype_of(node_by_name[a], 0)
                 for a in sym.list_auxiliary_states()]
    out_types = [_dtype_of(n, oi) for n, oi in sym._outputs]
    return (arg_shapes, out_shapes, aux_shapes,
            arg_types, out_types, aux_types)
