"""Executor — binds a Symbol and runs it as ONE jitted XLA program
(ref: src/executor/graph_executor.cc — GraphExecutor::SimpleBind/Forward/
Backward).

The reference's GraphExecutor does InferShape → PlanMemory → AttachOpExecs →
segmented engine pushes. Here bind() lowers the whole graph to a single
``jax.jit`` function: XLA buffer assignment plays PlanMemory, XLA fusion
plays bulk-exec segments, and ``jax.vjp`` over the traced program plays the
Gradient pass — no per-op dispatch remains on the hot path.
"""
from __future__ import annotations

import functools
import inspect

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .. import random as _random
from ..ndarray.ndarray import NDArray
from ..ops.registry import get_op

__all__ = ["Executor"]

# train-mode aux writebacks: op → {input_index: output_index}; in train mode
# the op's extra outputs are the updated mutable states for those inputs
# (ref: BatchNorm mutates moving_mean/moving_var in-kernel)
AUX_UPDATES = {"BatchNorm": {3: 1, 4: 2}}


from ..ops.registry import fn_params as _fn_params  # noqa: E402 — canonical home


def _call_op_with_attrs(op, attrs, train, arrays):
    """Invoke a registered op fn with symbol-node attrs as static params,
    injecting train_mode when the op takes it."""
    kwargs = {}
    accepted = _fn_params(op.fn)
    for k, v in attrs.items():
        if k.startswith("__") or k == "name":
            continue
        if isinstance(v, list):
            v = tuple(v)
        if accepted is None or k in accepted:
            kwargs[k] = v
    if accepted is not None and "train_mode" in accepted:
        kwargs["train_mode"] = bool(train)
    return op.fn(*arrays, **kwargs)


def _build_graph_fn(symbol, train):
    """Pure fn(args_dict, aux_dict, key) -> (outputs tuple, new_aux dict)."""
    nodes = symbol._topo_nodes()
    out_entries = [(id(n), oi) for n, oi in symbol._outputs]
    aux_names = set(symbol.list_auxiliary_states())

    def fn(arg_vals, aux_vals, key):
        with _random.key_scope(key):
            vals = {}
            new_aux = {}
            for node in nodes:
                if node.is_var():
                    if node.name in aux_names:
                        vals[(id(node), 0)] = aux_vals[node.name]
                    else:
                        vals[(id(node), 0)] = arg_vals[node.name]
                    continue
                op = get_op(node.op)
                ins = [vals[(id(inp), oi)] for inp, oi in node.inputs]
                out = _call_op_with_attrs(op, node.attrs, train, ins)
                outs = out if isinstance(out, tuple) else (out,)
                for i, o in enumerate(outs):
                    vals[(id(node), i)] = o
                if train and node.op in AUX_UPDATES:
                    for in_idx, out_idx in AUX_UPDATES[node.op].items():
                        if in_idx < len(node.inputs):
                            inp, _ = node.inputs[in_idx]
                            if inp.is_var() and inp.name in aux_names:
                                new_aux[inp.name] = jax.lax.stop_gradient(
                                    outs[out_idx])
            outputs = tuple(vals[e] for e in out_entries)
        return outputs, new_aux

    return fn


def _build_monitor_fn(symbol, train, monitor_all):
    """Like _build_graph_fn but returns every op-node output as a tap
    (plus variable nodes when ``monitor_all``) for mx.monitor.Monitor.
    Returns (names, fn) — names are static (jit outputs must be arrays),
    ``fn(...)`` yields the matching value tuple. Same key_scope so dropout
    masks etc. match the main forward."""
    nodes = symbol._topo_nodes()
    aux_names = set(symbol.list_auxiliary_states())

    names = []
    for node in nodes:
        if node.is_var():
            if monitor_all:
                names.append(node.name)
            continue
        for i in range(node.num_outputs):
            names.append(node.name + ("_output" if i == 0
                                      else "_output%d" % i))

    def fn(arg_vals, aux_vals, key):
        with _random.key_scope(key):
            vals = {}
            taps = []
            for node in nodes:
                if node.is_var():
                    v = aux_vals[node.name] if node.name in aux_names \
                        else arg_vals[node.name]
                    vals[(id(node), 0)] = v
                    if monitor_all:
                        taps.append(v)
                    continue
                op = get_op(node.op)
                ins = [vals[(id(inp), oi)] for inp, oi in node.inputs]
                out = _call_op_with_attrs(op, node.attrs, train, ins)
                outs = out if isinstance(out, tuple) else (out,)
                for i in range(node.num_outputs):
                    o = outs[i] if i < len(outs) else outs[0]
                    vals[(id(node), i)] = o
                    taps.append(o)
        return tuple(taps)

    return names, fn


class Executor:
    """Bound computation (ref: include/mxnet/executor.h — Executor)."""

    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None):
        self._symbol = symbol
        self._ctx = ctx
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()

        self.arg_dict = self._to_dict(args, arg_names, "args")
        missing = [n for n in arg_names if n not in self.arg_dict]
        if missing:
            raise MXNetError("bind: missing arguments %s" % (missing,))
        self.aux_dict = self._to_dict(aux_states or {}, aux_names,
                                      "aux_states")
        for n in aux_names:
            if n not in self.aux_dict:
                raise MXNetError("bind: missing auxiliary state %s" % (n,))

        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null") for n in arg_names}

        if args_grad is None:
            args_grad = {
                n: NDArray(jnp.zeros_like(self.arg_dict[n].data))
                for n in arg_names if self._grad_req[n] != "null"}
        self.grad_dict = self._to_dict(args_grad, arg_names, "args_grad")

        self.outputs = []
        self._fwd_cache = {}
        self._bwd_jit = None
        self._last = None  # (arg_datas, aux_datas, key) of last train fwd
        self._monitor_callback = None
        self._monitor_all = False
        self._mon_cache = {}

    @staticmethod
    def _to_dict(vals, names, what):
        if isinstance(vals, dict):
            out = {}
            for k, v in vals.items():
                out[k] = v if isinstance(v, NDArray) else NDArray(
                    jnp.asarray(v))
            return out
        if isinstance(vals, (list, tuple)):
            if len(vals) != len(names):
                raise MXNetError(
                    "%s: expected %d entries, got %d"
                    % (what, len(names), len(vals)))
            return {n: v if isinstance(v, NDArray) else NDArray(
                jnp.asarray(v)) for n, v in zip(names, vals)}
        raise MXNetError("%s must be dict or list" % what)

    # -- factory -------------------------------------------------------
    @staticmethod
    def simple_bind(symbol, ctx=None, grad_req="write", type_dict=None,
                    **kwargs):
        """Infer shapes from data shapes and allocate everything
        (ref: graph_executor.cc — GraphExecutor::Init via SimpleBind)."""
        arg_shapes, _, aux_shapes = symbol.infer_shape(**kwargs)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        type_dict = type_dict or {}
        # a var's declared __dtype__ (sym.var(dtype=...)) is the default
        # below an explicit type_dict entry — int8 params of a quantized
        # graph must not materialize as f32
        declared = {n.name: n.attrs["__dtype__"]
                    for n in symbol._topo_nodes()
                    if n.is_var() and "__dtype__" in n.attrs}
        args = {}
        for n, s in zip(arg_names, arg_shapes):
            dt = np.dtype(type_dict.get(n, declared.get(n, "float32")))
            args[n] = NDArray(jnp.zeros(s, dtype=dt))
        aux = {}
        for n, s in zip(aux_names, aux_shapes):
            dt = np.dtype(type_dict.get(n, declared.get(n, "float32")))
            aux[n] = NDArray(jnp.zeros(s, dtype=dt))
        return Executor(symbol, ctx, args, None, grad_req, aux)

    # -- execution -----------------------------------------------------
    def _get_fwd(self, train):
        jf = self._fwd_cache.get(train)
        if jf is None:
            jf = jax.jit(_build_graph_fn(self._symbol, train))
            self._fwd_cache[train] = jf
        return jf

    def set_monitor_callback(self, callback, monitor_all=False):
        """Install a per-node output tap (ref: MXExecutorSetMonitorCallbackEX
        — the engine invoked the callback per op; here forward additionally
        runs a jitted all-intermediates graph when a monitor is active).
        ``callback(name, NDArray)``; ``monitor_all`` also taps op inputs
        (the graph's variable nodes)."""
        self._monitor_callback = callback
        self._monitor_all = bool(monitor_all)
        self._mon_cache = {}

    def _run_monitor(self, train, arg_datas, aux_datas, key):
        # a Monitor outside its collection interval discards everything —
        # skip the (full duplicate) all-intermediates execution entirely
        owner = getattr(self._monitor_callback, "__self__", None)
        if owner is not None and hasattr(owner, "activated") \
                and not owner.activated:
            return
        cached = self._mon_cache.get(train)
        if cached is None:
            names, fn = _build_monitor_fn(self._symbol, train,
                                          self._monitor_all)
            cached = (names, jax.jit(fn))
            self._mon_cache[train] = cached
        names, jf = cached
        vals = jf(arg_datas, aux_datas, key)
        for name, val in zip(names, vals):
            self._monitor_callback(name, NDArray(val))

    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("forward: unknown argument %r" % k)
            data = v.data if isinstance(v, NDArray) else jnp.asarray(v)
            self.arg_dict[k]._set_data(
                data.astype(self.arg_dict[k].data.dtype)
                if data.dtype != self.arg_dict[k].data.dtype else data)
        arg_datas = {n: a.data for n, a in self.arg_dict.items()}
        aux_datas = {n: a.data for n, a in self.aux_dict.items()}
        key = _random.new_key()
        outs, new_aux = self._get_fwd(bool(is_train))(
            arg_datas, aux_datas, key)
        for n, v in new_aux.items():
            self.aux_dict[n]._set_data(v)
        self.outputs = [NDArray(o) for o in outs]
        self._last = (arg_datas, aux_datas, key) if is_train else None
        if self._monitor_callback is not None:
            self._run_monitor(bool(is_train), arg_datas, aux_datas, key)
        return self.outputs

    def _default_head_grads(self):
        from .symbol import LOSS_OPS

        grads = []
        for (node, oidx), out in zip(self._symbol._outputs, self.outputs):
            if node.op in LOSS_OPS:
                grads.append(jnp.ones_like(out.data))
            else:
                grads.append(jnp.zeros_like(out.data))
        return tuple(grads)

    def backward(self, out_grads=None):
        """Gradients of args with grad_req != 'null'
        (ref: GraphExecutor::Backward; loss-op heads imply ones cotangent,
        their custom vjp emits the fused loss gradient)."""
        if self._last is None:
            raise MXNetError(
                "backward called without forward(is_train=True)")
        arg_datas, aux_datas, key = self._last
        if out_grads is None:
            cts = self._default_head_grads()
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cts = tuple(
                g.data if isinstance(g, NDArray) else jnp.asarray(g)
                for g in out_grads)

        diff_names = tuple(sorted(
            n for n, r in self._grad_req.items() if r != "null"))
        if not diff_names:
            return

        if self._bwd_jit is None:
            fwd = _build_graph_fn(self._symbol, True)

            @jax.jit
            def bwd(diff_args, rest_args, aux_vals, k, cotangents):
                def f(d):
                    merged = dict(rest_args)
                    merged.update(d)
                    return fwd(merged, aux_vals, k)[0]

                _, vjp_fn = jax.vjp(f, diff_args)
                return vjp_fn(cotangents)[0]

            self._bwd_jit = bwd

        diff_args = {n: arg_datas[n] for n in diff_names}
        rest_args = {n: v for n, v in arg_datas.items()
                     if n not in diff_args}
        grads = self._bwd_jit(diff_args, rest_args, aux_datas, key, cts)
        for n in diff_names:
            g = grads[n]
            if self._grad_req[n] == "add":
                self.grad_dict[n]._set_data(self.grad_dict[n].data + g)
            else:
                self.grad_dict[n]._set_data(g.astype(
                    self.grad_dict[n].data.dtype))

    # -- utilities -----------------------------------------------------
    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._symbol.list_arguments()]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n)
                for n in self._symbol.list_arguments()]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n]
                for n in self._symbol.list_auxiliary_states()]

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for k, v in (arg_params or {}).items():
            if k in self.arg_dict:
                self.arg_dict[k]._set_data(
                    v.data.astype(self.arg_dict[k].dtype)
                    if isinstance(v, NDArray)
                    else jnp.asarray(v, self.arg_dict[k].dtype))
            elif not allow_extra_params:
                raise MXNetError("unknown parameter %r" % k)
        for k, v in (aux_params or {}).items():
            if k in self.aux_dict:
                self.aux_dict[k]._set_data(
                    v.data.astype(self.aux_dict[k].dtype)
                    if isinstance(v, NDArray)
                    else jnp.asarray(v, self.aux_dict[k].dtype))
            elif not allow_extra_params:
                raise MXNetError("unknown aux state %r" % k)

    def reshape(self, partial_shaping=False, allow_up_sizing=False,
                **kwargs):
        """Rebind with new data shapes (jit specializes per shape anyway)."""
        del partial_shaping, allow_up_sizing
        shapes = {}
        for n, arr in self.arg_dict.items():
            shapes[n] = kwargs.get(n, arr.shape)
        ex = Executor.simple_bind(
            self._symbol, self._ctx,
            grad_req={n: r for n, r in self._grad_req.items()},
            **{k: v for k, v in shapes.items()})
        ex.copy_params_from(
            {n: v for n, v in self.arg_dict.items() if n not in kwargs},
            dict(self.aux_dict), allow_extra_params=True)
        return ex
