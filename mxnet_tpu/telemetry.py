"""Runtime telemetry — typed metrics registry, step-phase spans,
distributed RPC tracing, and live export.

PRs 1-4 made the hot path *opaque by design*: one fused XLA launch per
step, a K-deep in-flight dispatch window, deferred host reads, and a
membership/KVStore layer that retries, fences, and renormalizes silently.
Understanding fused/compiled execution requires deliberate
instrumentation of launch behavior and phase timing ("Operator Fusion in
XLA: Analysis and Evaluation", PAPERS.md §fusion) — a handful of ad-hoc
scalar counters cannot answer "where did this step's time go" or "which
worker's RPC is slow". This module is the machine-readable layer under
``mx.profiler``:

1. **Typed metrics registry.** :class:`Counter` / :class:`Gauge` /
   :class:`Histogram` families with labels, created through one
   :class:`MetricsRegistry` (name-deduplicated, type-checked). Histograms
   use fixed log-scale buckets, are lock-guarded (observations arrive
   from the dispatch thread, deferred-read callbacks, and server
   connection threads), and are mergeable across instances. The old
   ``profiler._counters``/``_gauges`` dicts are now live views over this
   registry — ``profiler.counter_value``/``set_gauge`` keep working as
   shims.

2. **Step-phase spans.** The fused train paths record a per-step
   timeline — ``data_wait`` (DataLoader), ``dispatch`` (host work to
   launch the fused program), ``in_flight``/``retire`` (engine.StepStream
   token retirement) — as phase histograms plus optional JSONL span
   events. Retirement latency is measured from the timestamps the engine
   already keeps and lands inside the existing PendingValue
   materialization, so telemetry adds ZERO host syncs to the hot path
   (enforced statically by tools/check_host_syncs.py, which scans this
   module too).

3. **Distributed RPC tracing.** :func:`trace_scope` installs an ambient
   ``trace_id``; every async-server frame carries
   ``(trace_id, span_id, attempt)`` so a KVStore push/pull, membership
   heartbeat/register, or elastic rendezvous is correlatable end-to-end.
   Both sides record per-op latency/bytes/retry/fence metrics through
   :func:`record_rpc` and append to a bounded in-memory span log
   (:func:`rpc_spans`) plus the JSONL sink.

4. **Export.** ``MXT_TELEMETRY_JSONL=path`` activates a buffered
   JSONL event/metric sink (writer thread; ``flush()`` is called by
   ``nd.waitall()`` and the estimator at epoch end).
   :func:`render_prometheus` produces the text exposition format and
   ``MXT_TELEMETRY_PORT`` serves it from a stdlib HTTP endpoint
   (loopback-only — the async-server threat model applies to anything
   that listens). ``tools/mxt_top.py`` tails either and renders a live
   console.
"""
from __future__ import annotations

import bisect
import collections
import json
import os
import queue
import re
import threading
import time

from .base import MXNetError

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "registry",
    "counter", "gauge", "histogram", "render_prometheus",
    "registry_export",
    "emit_event", "flush", "jsonl_path",
    "add_event_tap", "remove_event_tap",
    "record_phase", "record_dispatch", "record_step_retired",
    "record_compile", "record_compile_cache", "record_tune_lookup",
    "trace_scope", "current_trace_id", "new_trace_id", "new_span_id",
    "record_rpc", "rpc_spans", "clear_rpc_spans",
    "record_trace_span", "trace_spans", "clear_trace_spans",
    "start_http_server", "http_port", "histogram_quantile",
    "sanitize_metric_name",
]


# --------------------------------------------------------------------------
# metric families
# --------------------------------------------------------------------------
_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_metric_name(name):
    """Coerce an arbitrary string (e.g. a profiler counter name) into a
    valid Prometheus metric name."""
    name = _NAME_RE.sub("_", str(name))
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def _fmt(v):
    """Numeric rendering: integral values print without a decimal point
    so counters read naturally ('value=3', not 'value=3.0')."""
    s = "%.10g" % v
    return s


class _ScalarChild:
    """One (labelset, value) cell of a Counter/Gauge family."""

    __slots__ = ("_lock", "_v")

    def __init__(self):
        self._lock = threading.Lock()
        self._v = 0.0

    def inc(self, n=1):
        with self._lock:
            self._v += n

    def dec(self, n=1):
        self.inc(-n)

    def set(self, v):
        with self._lock:
            self._v = v

    def reset(self):
        """Zero the cell; returns the previous value (the profiler's
        reset_*_count shims ride this)."""
        with self._lock:
            prev, self._v = self._v, 0.0
        return prev

    @property
    def value(self):
        return self._v

    def merge(self, other):
        self.inc(other.value)


class _HistChild:
    """One labelset's bucket state: counts per bucket (+Inf last), sum,
    total count. Lock-guarded — observations arrive from many threads."""

    __slots__ = ("_lock", "_bounds", "counts", "sum", "count")

    def __init__(self, bounds):
        self._lock = threading.Lock()
        self._bounds = bounds
        self.counts = [0] * (len(bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v):
        i = bisect.bisect_left(self._bounds, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def snapshot(self):
        with self._lock:
            return {"buckets": tuple(self._bounds),
                    "counts": list(self.counts),
                    "sum": self.sum, "count": self.count}

    def merge(self, other):
        """Fold another child (or snapshot dict) with IDENTICAL buckets
        into this one — the cross-instance aggregation primitive."""
        snap = other.snapshot() if hasattr(other, "snapshot") else other
        if tuple(snap["buckets"]) != tuple(self._bounds):
            raise MXNetError(
                "cannot merge histograms with different buckets")
        with self._lock:
            for i, c in enumerate(snap["counts"]):
                self.counts[i] += c
            self.sum += snap["sum"]
            self.count += snap["count"]

    def quantile(self, q):
        return histogram_quantile(q, self._bounds, list(self.counts))


def histogram_quantile(q, bounds, counts):
    """Approximate quantile from per-bucket counts (``counts`` has one
    extra +Inf cell). Returns the upper bound of the bucket the rank
    falls in (log-scale buckets make this a <=4x estimate)."""
    total = sum(counts)
    if total == 0:
        return 0.0
    rank = q * total
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= rank and c:
            if i < len(bounds):
                return bounds[i]
            return bounds[-1] if bounds else 0.0
    return bounds[-1] if bounds else 0.0


class _Family:
    """A named metric with a fixed label schema; children are
    deduplicated per label-values tuple."""

    kind = None

    def __init__(self, name, help="", labelnames=()):
        self.name = sanitize_metric_name(name)
        self.help = str(help)
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children = {}
        self._default = None

    def _make_child(self):
        raise NotImplementedError

    def labels(self, *values, **kv):
        """The child for one label-values set (created on first use,
        the SAME object on every later call — label dedup)."""
        if kv:
            if values:
                raise MXNetError("pass labels positionally or by name, "
                                 "not both")
            try:
                values = tuple(str(kv.pop(k)) for k in self.labelnames)
            except KeyError as e:
                raise MXNetError("metric %s is missing label %s"
                                 % (self.name, e)) from e
            if kv:
                raise MXNetError("metric %s has no label(s) %s"
                                 % (self.name, sorted(kv)))
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise MXNetError(
                "metric %s takes labels %s, got %d value(s)"
                % (self.name, self.labelnames, len(values)))
        with self._lock:
            ch = self._children.get(values)
            if ch is None:
                ch = self._children[values] = self._make_child()
            return ch

    @property
    def default(self):
        """The no-labels child (only valid for an unlabeled family)."""
        ch = self._default
        if ch is None:
            ch = self._default = self.labels()
        return ch

    def children(self):
        with self._lock:
            return dict(self._children)


class Counter(_Family):
    """Monotonically increasing count (``reset()`` exists only for the
    profiler shims' reset semantics)."""

    kind = "counter"

    def _make_child(self):
        return _ScalarChild()

    def inc(self, n=1):
        self.default.inc(n)

    def reset(self):
        return self.default.reset()

    @property
    def value(self):
        return self.default.value


class Gauge(_Family):
    """Point-in-time value."""

    kind = "gauge"

    def _make_child(self):
        return _ScalarChild()

    def set(self, v):
        self.default.set(v)

    def inc(self, n=1):
        self.default.inc(n)

    def dec(self, n=1):
        self.default.dec(n)

    @property
    def value(self):
        return self.default.value


# log-scale bounds covering 1 microsecond .. ~18 minutes in x4 steps —
# wide enough for a host-side phase (~us), a fused step (~ms), an axon
# tunnel RPC (~100ms), and a checkpoint/epoch (~minutes)
DEFAULT_BUCKETS = tuple(1e-6 * 4.0 ** i for i in range(16))


class Histogram(_Family):
    """Fixed-bucket (log-scale by default) distribution."""

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(),
                 buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        bounds = tuple(sorted(set(buckets)))
        if not bounds:
            raise MXNetError("histogram %s needs at least one bucket "
                             "bound" % self.name)
        self.buckets = bounds

    def _make_child(self):
        return _HistChild(self.buckets)

    def observe(self, v):
        self.default.observe(v)

    def merge(self, other):
        """Fold another family's children into this one (same buckets,
        matching label schema)."""
        if getattr(other, "buckets", None) != self.buckets:
            raise MXNetError(
                "cannot merge histograms with different buckets")
        for values, child in other.children().items():
            self.labels(*values).merge(child)

    def snapshot(self):
        return self.default.snapshot()

    def quantile(self, q):
        return self.default.quantile(q)


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Name-keyed collection of metric families. ``counter/gauge/
    histogram`` are get-or-create: the same name returns the SAME family
    (a kind or label-schema mismatch is a hard error, not a silent
    second metric)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        name = sanitize_metric_name(name)
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls:
                    raise MXNetError(
                        "telemetry metric %r is already registered as a "
                        "%s, not a %s" % (name, m.kind, cls.kind))
                if m.labelnames != tuple(labelnames):
                    raise MXNetError(
                        "telemetry metric %r is already registered with "
                        "labels %s" % (name, m.labelnames))
                if kw.get("buckets") is not None and \
                        tuple(sorted(set(kw["buckets"]))) != m.buckets:
                    raise MXNetError(
                        "telemetry histogram %r is already registered "
                        "with different buckets" % name)
                return m
            m = cls(name, help, labelnames, **{k: v for k, v in kw.items()
                                               if v is not None})
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=()):
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()):
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(), buckets=None):
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name, default=None):
        with self._lock:
            return self._metrics.get(sanitize_metric_name(name), default)

    def unregister(self, name):
        """Drop a family (the profiler's dumps(reset=True) shim)."""
        with self._lock:
            self._metrics.pop(sanitize_metric_name(name), None)

    def collect(self):
        """[(family, {labelvalues: child})] sorted by name — one
        consistent snapshot of the family LIST (children snapshot
        individually under their own locks)."""
        with self._lock:
            fams = sorted(self._metrics.values(), key=lambda m: m.name)
        return [(m, m.children()) for m in fams]

    def snapshot_values(self):
        """Compact {name: value | {'count','sum'}} dict (the JSONL
        metrics row)."""
        out = {}
        for fam, children in self.collect():
            for values, ch in sorted(children.items()):
                key = fam.name if not values else \
                    "%s{%s}" % (fam.name, ",".join(
                        "%s=%s" % kv for kv in zip(fam.labelnames, values)))
                if fam.kind == "histogram":
                    snap = ch.snapshot()
                    out[key] = {"count": snap["count"],
                                "sum": round(snap["sum"], 9)}
                else:
                    out[key] = ch.value
        return out

    def export(self):
        """Serializable full-registry snapshot — the ``tel_snapshot``
        wire payload the fleet collector (telemetry_fleet.py) scrapes:
        one dict per family (name/kind/help/labelnames, histogram
        buckets) with every child's current value or bucket snapshot.
        Pure host data; picklable and JSON-able."""
        fams = []
        for fam, children in self.collect():
            d = {"name": fam.name, "kind": fam.kind, "help": fam.help,
                 "labelnames": list(fam.labelnames)}
            if fam.kind == "histogram":
                d["buckets"] = list(fam.buckets)
            ch = []
            for values, child in sorted(children.items()):
                if fam.kind == "histogram":
                    snap = child.snapshot()
                    ch.append([list(values),
                               {"counts": list(snap["counts"]),
                                "sum": snap["sum"],
                                "count": snap["count"]}])
                else:
                    ch.append([list(values), child.value])
            d["children"] = ch
            fams.append(d)
        return {"ts": round(time.time(), 6), "families": fams}

    def render_prometheus(self):
        """Text exposition format (the /metrics payload)."""
        lines = []
        for fam, children in self.collect():
            if fam.help:
                lines.append("# HELP %s %s"
                             % (fam.name, fam.help.replace("\n", " ")))
            lines.append("# TYPE %s %s" % (fam.name, fam.kind))
            for values, ch in sorted(children.items()):
                base = _label_str(fam.labelnames, values)
                if fam.kind == "histogram":
                    snap = ch.snapshot()
                    cum = 0
                    for bound, c in zip(snap["buckets"], snap["counts"]):
                        cum += c
                        lines.append("%s_bucket%s %d" % (
                            fam.name,
                            _label_str(fam.labelnames + ("le",),
                                       values + (_fmt(bound),)), cum))
                    lines.append("%s_bucket%s %d" % (
                        fam.name,
                        _label_str(fam.labelnames + ("le",),
                                   values + ("+Inf",)), snap["count"]))
                    lines.append("%s_sum%s %s" % (fam.name, base,
                                                  _fmt(snap["sum"])))
                    lines.append("%s_count%s %d" % (fam.name, base,
                                                    snap["count"]))
                else:
                    lines.append("%s%s %s" % (fam.name, base,
                                              _fmt(ch.value)))
        return "\n".join(lines) + "\n"


def _label_str(names, values):
    if not names:
        return ""
    esc = [str(v).replace("\\", "\\\\").replace('"', '\\"')
           .replace("\n", "\\n") for v in values]
    return "{%s}" % ",".join('%s="%s"' % (n, v)
                             for n, v in zip(names, esc))


_REGISTRY = MetricsRegistry()


def registry():
    """The process-default registry (what render_prometheus and the
    profiler shims use)."""
    return _REGISTRY


def counter(name, help="", labelnames=()):
    return _REGISTRY.counter(name, help, labelnames)


def gauge(name, help="", labelnames=()):
    return _REGISTRY.gauge(name, help, labelnames)


def histogram(name, help="", labelnames=(), buckets=None):
    return _REGISTRY.histogram(name, help, labelnames, buckets=buckets)


def render_prometheus():
    return _REGISTRY.render_prometheus()


def registry_export():
    """The process registry as a serializable snapshot (what the
    ``tel_snapshot`` async-server op answers with)."""
    return _REGISTRY.export()


# --------------------------------------------------------------------------
# JSONL event sink
# --------------------------------------------------------------------------
_STOP = object()


class JsonlSink:
    """Buffered JSONL writer: ``emit`` enqueues (never blocks the hot
    path — overflow drops and counts), a daemon thread writes, and
    ``flush`` round-trips a marker through the queue so everything
    enqueued before it is durably on disk."""

    def __init__(self, path):
        self.path = path
        self._q = queue.Queue(maxsize=100000)
        self.dropped = 0
        self._file = open(path, "a")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="mxt-telemetry-jsonl")
        self._thread.start()

    def emit(self, row):
        try:
            self._q.put_nowait(row)
        except queue.Full:
            self.dropped += 1

    def _loop(self):
        while True:
            item = self._q.get()
            if item is _STOP:
                self._file.flush()
                return
            if isinstance(item, threading.Event):
                self._file.flush()
                item.set()
                continue
            try:
                self._file.write(json.dumps(item) + "\n")
            except (TypeError, ValueError):
                self.dropped += 1  # non-serializable row: drop, keep going

    def flush(self, timeout=10.0):
        """Block until every row enqueued before this call is written
        and the file is flushed."""
        if not self._thread.is_alive():
            return
        ev = threading.Event()
        self._q.put(ev)
        ev.wait(timeout)

    def close(self):
        self._q.put(_STOP)
        self._thread.join(timeout=10.0)
        try:
            self._file.close()
        except OSError:
            pass


_sink_lock = threading.Lock()
_sink = None
_sink_path = None


def _active_sink():
    """The JSONL sink for the CURRENT ``MXT_TELEMETRY_JSONL`` value —
    re-reading the config each call keeps tests (monkeypatched env) and
    long-lived processes honest; path changes swap the sink."""
    global _sink, _sink_path
    from . import config

    path = config.get("MXT_TELEMETRY_JSONL")
    if path == _sink_path:
        return _sink
    with _sink_lock:
        if path != _sink_path:
            old, _sink, _sink_path = _sink, None, path
            if old is not None:
                old.close()
            if path:
                _sink = JsonlSink(path)
    return _sink


def jsonl_path():
    s = _active_sink()
    return s.path if s is not None else None


# event taps: callables fed every event row BEFORE the JSONL sink —
# the diagnostics flight recorder rides one, so every existing event
# source (spans, RPC spans, membership/reshard/checkpoint events) lands
# in the post-mortem ring without any source changing. Taps are host
# bookkeeping and must never raise into the emitter.
_event_taps = []


def add_event_tap(fn):
    if fn not in _event_taps:
        _event_taps.append(fn)


def remove_event_tap(fn):
    try:
        _event_taps.remove(fn)
    except ValueError:
        pass


def _events_active():
    """True when building an event row has a consumer (sink or tap)."""
    return _event_taps or _active_sink() is not None


def _dispatch_row(row):
    for fn in list(_event_taps):
        try:
            fn(row)
        except Exception:  # noqa: BLE001 — a broken tap must not stop events
            pass
    s = _active_sink()
    if s is not None:
        s.emit(row)


def emit_event(kind, **fields):
    """Queue one event row to the taps + JSONL sink (no-op when neither
    is active)."""
    if not _events_active():
        return
    row = {"ts": round(time.time(), 6), "kind": str(kind)}
    row.update(fields)
    _dispatch_row(row)


def flush(write_metrics=False):
    """Flush the JSONL sink (called by ``nd.waitall()`` and the
    estimator at epoch end). ``write_metrics=True`` also appends one
    compact metrics-snapshot row before flushing."""
    s = _active_sink()
    if s is None:
        return
    if write_metrics:
        s.emit({"ts": round(time.time(), 6), "kind": "metrics",
                "data": _REGISTRY.snapshot_values()})
    s.flush()


# --------------------------------------------------------------------------
# step-phase spans
# --------------------------------------------------------------------------
_phase_hist = None
_latency_hist = None
_depth_hist = None


def record_phase(phase, seconds, stream=None, step=None):
    """One step-phase observation: ``data_wait`` / ``dispatch`` /
    ``in_flight`` / ``retire``. Lands in the
    ``mxt_step_phase_seconds{phase=}`` histogram and (sink active) a
    JSONL span event. Host-side wall clock only — never a device read."""
    global _phase_hist
    h = _phase_hist
    if h is None:
        h = _phase_hist = histogram(
            "mxt_step_phase_seconds",
            "Per-step phase timing: data_wait -> dispatch -> in_flight "
            "-> retire.", ("phase",))
    h.labels(phase).observe(seconds)
    if _events_active():
        emit_event("span", name=str(phase), stream=stream, step=step,
                   seconds=round(seconds, 9))


def record_dispatch(stream, step, depth):
    """Dispatch-depth occupancy at the moment a fused step was pushed
    into the engine window."""
    global _depth_hist
    h = _depth_hist
    if h is None:
        h = _depth_hist = histogram(
            "mxt_dispatch_depth_occupancy",
            "In-flight fused steps at each dispatch (window occupancy).",
            buckets=tuple(range(1, 17)))
    h.observe(depth)
    if _events_active():
        emit_event("span", name="dispatch", stream=stream, step=step,
                   depth=depth)


def record_step_retired(stream, step, latency_s):
    """One fused step observed on host: dispatch->retire latency,
    measured inside the engine's EXISTING deferred read (zero new
    syncs). Exactly one of these per dispatched step."""
    global _latency_hist
    h = _latency_hist
    if h is None:
        h = _latency_hist = histogram(
            "mxt_step_latency_seconds",
            "Fused-step dispatch->retire latency (how long a step rode "
            "the in-flight window).", ("stream",))
    h.labels(stream).observe(latency_s)
    record_phase("in_flight", latency_s, stream=stream, step=step)
    if _events_active():
        emit_event("span", name="retire", stream=stream, step=step,
                   latency_s=round(latency_s, 9))


# --------------------------------------------------------------------------
# compile + tuning observability (fed by tuning/compile_cache.py's
# jax.monitoring listeners and tuning/table.py lookups)
# --------------------------------------------------------------------------
_compile_hist = None
_compile_total = None
_compile_cache_c = None
_tune_cache_c = None


def record_compile(phase, seconds):
    """One XLA compilation-pipeline phase observation
    (``trace``/``lower``/``compile``) — lands in
    ``mxt_compile_seconds{phase=}``; the ``compile`` phase also bumps
    ``mxt_compiles_total``. Cold-vs-warm cost in one histogram: a
    persistent-cache hit still reports here, as a ~ms deserialization
    instead of a full XLA run."""
    global _compile_hist, _compile_total
    if _compile_hist is None:
        _compile_hist = histogram(
            "mxt_compile_seconds",
            "JIT pipeline time per phase: trace (python->jaxpr), lower "
            "(jaxpr->StableHLO), compile (XLA backend, incl. persistent-"
            "cache deserialization on hits).", ("phase",))
        _compile_total = counter(
            "mxt_compiles_total",
            "Compiled-program builds dispatched to the XLA backend "
            "(persistent-cache hits included; see "
            "mxt_compile_cache_misses_total for true JIT compiles).")
    _compile_hist.labels(phase).observe(seconds)
    if phase == "compile":
        _compile_total.inc()
    # compile time is lost wall-clock: the diagnostics goodput ledger
    # (and the flight recorder) consume this via the event taps
    if _events_active():
        emit_event("compile", phase=str(phase),
                   seconds=round(seconds, 9))


def record_compile_cache(hit):
    """One persistent-compilation-cache outcome. A warm-started process
    shows hits only; a hot loop showing misses is paying JIT on the
    request path — the exact regression the warmup contract forbids."""
    global _compile_cache_c
    if _compile_cache_c is None:
        _compile_cache_c = counter(
            "mxt_compile_cache_total",
            "Persistent compilation cache lookups by outcome.",
            ("outcome",))
    _compile_cache_c.labels("hit" if hit else "miss").inc()


def record_tune_lookup(hit):
    """One tuning-table lookup outcome (mxt_tune_cache_hits_total /
    mxt_tune_cache_misses_total — a miss means the autotuner ran a
    measurement or cost-model pass for a new shape bucket)."""
    global _tune_cache_c
    if _tune_cache_c is None:
        _tune_cache_c = (
            counter("mxt_tune_cache_hits_total",
                    "Tuning-table lookups answered from the table."),
            counter("mxt_tune_cache_misses_total",
                    "Tuning-table lookups that fell through to "
                    "measurement or the heuristic cost model."))
    _tune_cache_c[0 if hit else 1].inc()


# --------------------------------------------------------------------------
# distributed RPC tracing
# --------------------------------------------------------------------------
_trace = threading.local()


def new_trace_id():
    return os.urandom(8).hex()


def new_span_id():
    return os.urandom(4).hex()


def current_trace_id():
    return getattr(_trace, "tid", None)


class trace_scope:
    """Install an ambient trace id for the current thread; every
    AsyncClient frame sent inside the scope carries it. Nested scopes
    keep the outer id unless an explicit one is given — so one logical
    op (a multi-key push) is one trace."""

    def __init__(self, trace_id=None):
        self._explicit = trace_id

    def __enter__(self):
        self._prev = current_trace_id()
        tid = self._explicit or self._prev or new_trace_id()
        _trace.tid = tid
        return tid

    def __exit__(self, *exc):
        _trace.tid = self._prev
        return False


_RPC_SPAN_LOG = collections.deque(maxlen=1024)
_rpc_hist = None
_rpc_bytes = None
_rpc_total = None
_rpc_retries = None
_rpc_fenced = None


def record_rpc(side, op, seconds=None, nbytes=None, status="ok",
               trace=None, key=None):
    """One RPC observation from either endpoint. ``trace`` is the
    ``(trace_id, span_id, attempt)`` tuple riding the frame (or None for
    an untraced peer). Feeds the per-op latency/bytes/total/retry/fence
    metrics, the bounded in-memory span log, and the JSONL sink."""
    global _rpc_hist, _rpc_bytes, _rpc_total, _rpc_retries, _rpc_fenced
    if _rpc_hist is None:
        _rpc_hist = histogram(
            "mxt_kvstore_rpc_latency_seconds",
            "KVStore/membership RPC latency per op.", ("side", "op"))
        _rpc_bytes = histogram(
            "mxt_kvstore_rpc_bytes",
            "KVStore/membership RPC payload bytes per op.",
            ("side", "op"),
            buckets=tuple(4.0 ** i for i in range(2, 16)))
        _rpc_total = counter(
            "mxt_kvstore_rpc_total",
            "KVStore/membership RPCs by op and reply status.",
            ("side", "op", "status"))
        _rpc_retries = counter(
            "mxt_kvstore_rpc_retries_total",
            "RPC frames that were retry attempts (attempt > 0).",
            ("side", "op"))
        _rpc_fenced = counter(
            "mxt_kvstore_fenced_frames_total",
            "Frames refused by stale-worker fencing.", ("op",))
    op = str(op)
    side = str(side)
    status = str(status)
    if seconds is not None:
        _rpc_hist.labels(side, op).observe(seconds)
    if nbytes:
        _rpc_bytes.labels(side, op).observe(nbytes)
    _rpc_total.labels(side, op, status).inc()
    trace_id, span_id, attempt = (trace or (None, None, 0))
    if attempt:
        _rpc_retries.labels(side, op).inc()
    if status == "stale" and side == "server":
        _rpc_fenced.labels(op).inc()
    entry = {"ts": round(time.time(), 6), "side": side, "op": op,
             "key": key, "status": status, "trace_id": trace_id,
             "span_id": span_id, "attempt": attempt,
             "latency_s": None if seconds is None else round(seconds, 9),
             "bytes": nbytes}
    _RPC_SPAN_LOG.append(entry)
    if _events_active():
        _dispatch_row(dict(entry, kind="rpc_span"))


_emb_rpcs = None
_emb_bytes = None
_emb_pull_hist = None


def record_embedding_rpc(op, nbytes=0):
    """One sharded-embedding data RPC (embedding/client.py): per-op
    totals plus row-payload bytes split by direction — the numerator of
    the ``embedding_bytes_per_sec`` bench metric."""
    global _emb_rpcs, _emb_bytes
    if _emb_rpcs is None:
        _emb_rpcs = counter(
            "mxt_embedding_rpcs_total",
            "Sharded-embedding data RPCs by op (one per destination "
            "server per batched push/pull).", ("op",))
        _emb_bytes = counter(
            "mxt_embedding_bytes_total",
            "Embedding row bytes moved over the fleet transport.",
            ("dir",))
    _emb_rpcs.labels(str(op)).inc()
    if nbytes:
        _emb_bytes.labels("push" if op == "emb_push" else "pull").inc(
            int(nbytes))


def record_embedding_pull(seconds):
    """End-to-end latency of one ShardedEmbedding.pull (cache hits and
    server fetches included) — mxt_top's embedding p50/p99 source."""
    global _emb_pull_hist
    if _emb_pull_hist is None:
        _emb_pull_hist = histogram(
            "mxt_embedding_pull_seconds",
            "ShardedEmbedding.pull latency (device cache + fleet "
            "fetch).")
    _emb_pull_hist.observe(seconds)


def rpc_spans():
    """The bounded in-memory RPC span log (newest last) — what the
    trace-propagation test and mxt_top's JSONL mode read."""
    return list(_RPC_SPAN_LOG)


def clear_rpc_spans():
    _RPC_SPAN_LOG.clear()


# --------------------------------------------------------------------------
# request-lifecycle trace spans (the distributed tracing layer the
# fleet collector reassembles — telemetry_fleet.py)
# --------------------------------------------------------------------------
# Bounded like the RPC span log: old traces age out, appends never
# block. One row per closed span: the serving router/scheduler stamp
# queue/prefill/decode/commit spans against the request's trace_id from
# host wall clocks they already keep (spans CLOSE inside the existing
# deferred PendingValue retirement, so the layer adds zero device
# syncs — the mxt_step_latency_seconds discipline).
_TRACE_SPAN_LOG = collections.deque(maxlen=8192)


def record_trace_span(name, trace_id, t0, t1, clock_now=None,
                      track=None, **attrs):
    """Record one closed span of a distributed request trace.

    ``t0``/``t1`` are in the CALLER's clock (``time.monotonic`` or a
    test fake); ``clock_now`` is that clock's current reading, used to
    shift the span onto the wall-clock epoch so spans from different
    processes line up in one timeline. ``track`` names the timeline row
    ("router", "replica-0", ...). Returns the stored row (or None when
    ``trace_id`` is None — untraced requests cost nothing)."""
    if trace_id is None:
        return None
    off = 0.0 if clock_now is None else time.time() - clock_now
    row = {"kind": "trace_span", "name": str(name),
           "trace_id": str(trace_id), "span_id": new_span_id(),
           "track": None if track is None else str(track),
           "t0": round(float(t0) + off, 6),  # sync-ok: host wall-clock scalar
           "t1": round(float(t1) + off, 6)}  # sync-ok: host wall-clock scalar
    if attrs:
        row["attrs"] = {k: v for k, v in attrs.items() if v is not None}
    _TRACE_SPAN_LOG.append(row)
    if _events_active():
        _dispatch_row(dict(row))
    return row


def trace_spans(trace_id=None):
    """The bounded request-trace span log (oldest first), optionally
    filtered to one trace — the ``tel_spans`` wire payload."""
    rows = list(_TRACE_SPAN_LOG)
    if trace_id is None:
        return rows
    return [r for r in rows if r["trace_id"] == trace_id]


def clear_trace_spans():
    _TRACE_SPAN_LOG.clear()


# --------------------------------------------------------------------------
# HTTP exposition endpoint
# --------------------------------------------------------------------------
_http_server = None
_http_lock = threading.Lock()


def start_http_server(port=None):
    """Serve ``render_prometheus()`` on ``127.0.0.1:port`` from a daemon
    thread (port 0 picks a free one; see :func:`http_port`). Loopback
    only — the exposition is plain text but the listening posture
    follows async_server.py's threat model."""
    global _http_server
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            path, _, query = self.path.partition("?")
            if path.startswith("/debug/"):
                # diagnostics debug routes (stacks / memory /
                # flightrecorder / trace / timeline) ride the same
                # endpoint so one scrape target serves both metrics and
                # post-mortems
                try:
                    from . import diagnostics

                    status, ctype, body = diagnostics.handle_debug(
                        path, query)
                except Exception as e:  # noqa: BLE001 — a debug route
                    # must never take the exposition endpoint down
                    status, ctype = 500, "text/plain; charset=utf-8"
                    body = ("debug route error: %s" % e).encode("utf-8")
            elif path == "/fleet":
                # the fleet collector's merged view (member-labeled
                # samples from every scraped fleet member) — what
                # `mxt_top --fleet` tails
                try:
                    from . import telemetry_fleet

                    c = telemetry_fleet.default_collector()
                    if c is None:
                        status = 404
                        ctype = "text/plain; charset=utf-8"
                        body = (b"no fleet collector is running in this "
                                b"process (telemetry_fleet.FleetCollector"
                                b" + set_default_collector)")
                    else:
                        status = 200
                        ctype = ("text/plain; version=0.0.4; "
                                 "charset=utf-8")
                        body = c.render_prometheus().encode("utf-8")
                except Exception as e:  # noqa: BLE001 — see above
                    status, ctype = 500, "text/plain; charset=utf-8"
                    body = ("fleet route error: %s" % e).encode("utf-8")
            elif path == "/health":
                # the training-health plane's rule verdicts + anomaly
                # summary (200 ok / 503 degraded — the load-balancer
                # health-check contract)
                try:
                    from . import health

                    status, ctype, body = health.handle_health()
                    body = body.encode("utf-8")
                except Exception as e:  # noqa: BLE001 — see above
                    status, ctype = 500, "text/plain; charset=utf-8"
                    body = ("health route error: %s" % e).encode("utf-8")
            else:
                status = 200
                ctype = "text/plain; version=0.0.4; charset=utf-8"
                body = render_prometheus().encode("utf-8")
            try:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError):
                pass  # client hung up mid-transfer (big trace bodies)

        def log_message(self, *args):
            pass  # metrics scrapes must not spam the training logs

    with _http_lock:
        if _http_server is not None:
            return _http_server
        if port is None:
            from . import config

            port = config.get("MXT_TELEMETRY_PORT")
        if port is None:
            raise MXNetError(
                "no telemetry port: pass one or set MXT_TELEMETRY_PORT")
        srv = ThreadingHTTPServer(("127.0.0.1", int(port)), _Handler)
        srv.daemon_threads = True
        threading.Thread(target=srv.serve_forever, daemon=True,
                         name="mxt-telemetry-http").start()
        _http_server = srv
    return srv


def http_port():
    """The bound exposition port, or None when no server is running."""
    return None if _http_server is None else \
        _http_server.server_address[1]


def _maybe_autostart():
    """Start the exposition endpoint when MXT_TELEMETRY_PORT is set
    (called once at package import)."""
    try:
        from . import config

        if config.get("MXT_TELEMETRY_PORT") is not None \
                and _http_server is None:
            start_http_server()
    except Exception:
        pass  # observability must never take the process down
