"""Asynchronous parameter server — the ps-lite/hogwild analog
(ref: src/kvstore/kvstore_dist_server.h — KVStoreDistServer::DataHandleEx
async branch: each worker's push is applied to the server-side weight the
moment it arrives, with NO cross-worker barrier; pulls return whatever
the weight currently is).

TPU-native placement note: synchronous data parallelism compiles into the
training step as XLA collectives (parallel/sharded.py) — that path never
touches this module. True ASYNC semantics cannot ride collectives (they
are barriers by construction), so dist_async gets what the reference has:
a parameter-server process. Here it is a thread inside worker 0 speaking
length-prefixed pickles over TCP; the server address derives from the
launcher's coordinator (MXT_COORDINATOR host, port + ASYNC_PORT_OFFSET).

Asynchrony is BETWEEN WORKERS: no worker ever waits for another's push
(the reference's async contract). Application at the server is
serialized by a store lock, matching ps-lite's per-server customer
thread, which handles one message at a time — "lock-free" in the
reference describes the absence of worker-side barriers, not racy
read-modify-write on the server. A push is fully applied before its
ack, so each worker's own pushes are totally ordered.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading

from .base import MXNetError

ASYNC_PORT_OFFSET = 1717

__all__ = ["AsyncParamServer", "AsyncClient", "server_address",
           "get_server", "ASYNC_PORT_OFFSET"]

_SERVERS = {}  # (host, port) -> AsyncParamServer (one bind per process)


def get_server(host, port):
    """Process-wide server singleton: re-creating a dist_async KVStore
    must not re-bind the port (EADDRINUSE); a new store generation
    RESETs the existing server instead."""
    key = (host, port)
    if key not in _SERVERS:
        _SERVERS[key] = AsyncParamServer(host, port)
    return _SERVERS[key]


def server_address():
    """host:port of the async server, derived from MXT_COORDINATOR."""
    coord = os.environ.get("MXT_COORDINATOR")
    if not coord or ":" not in coord:
        return None
    host, _, port = coord.rpartition(":")
    return host, int(port) + ASYNC_PORT_OFFSET


def _send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("!Q", len(payload)) + payload)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("async kvstore peer closed")
        buf += chunk
    return buf


def _recv_msg(sock):
    (n,) = struct.unpack("!Q", _recv_exact(sock, 8))
    return pickle.loads(_recv_exact(sock, n))


class AsyncParamServer:
    """Threaded TCP server holding weights + the server-side optimizer."""

    def __init__(self, host, port):
        self._store = {}     # key -> np.ndarray (the weight)
        self._updater = None
        self._mutate = threading.Lock()  # ps-lite customer-thread analog
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="kv-async-accept")
        self._accept_thread.start()

    # -- server side -------------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed during shutdown
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True, name="kv-async-conn").start()

    def _serve(self, conn):
        from .ndarray.ndarray import NDArray
        import numpy as np
        import jax.numpy as jnp

        try:
            while True:
                op, key, payload = _recv_msg(conn)
                if isinstance(key, str) and key.isdigit():
                    # the eager updater keys optimizer state and lr/wd
                    # multipliers by int for digit keys (kvstore.py push)
                    key = int(key)
                if op == "reset":
                    with self._mutate:
                        self._store.clear()
                        self._updater = None
                    _send_msg(conn, ("ok", None))
                elif op == "init":
                    with self._mutate:
                        # first writer wins (every worker sends its init)
                        self._store.setdefault(key, np.array(payload))
                    _send_msg(conn, ("ok", None))
                elif op == "push":
                    with self._mutate:
                        w = self._store.get(key)
                        if w is None:
                            # first push initializes, like KVStoreLocal
                            self._store[key] = np.array(payload)
                            _send_msg(conn, ("ok", None))
                            continue
                        if self._updater is not None:
                            w_nd = NDArray(jnp.asarray(w))
                            self._updater(key,
                                          NDArray(jnp.asarray(payload)),
                                          w_nd)
                            self._store[key] = np.asarray(w_nd.data)
                        else:
                            # replace semantics, matching the local
                            # no-updater path (CopyFromTo(merged, &local))
                            self._store[key] = np.array(payload)
                    _send_msg(conn, ("ok", None))
                elif op == "pull":
                    w = self._store.get(key)
                    if w is None:
                        _send_msg(conn, ("err",
                                         "key %r not initialized" % key))
                    else:
                        _send_msg(conn, ("ok", np.array(w)))
                elif op == "set_optimizer":
                    from . import optimizer as opt

                    with self._mutate:
                        self._updater = opt.get_updater(
                            pickle.loads(payload))
                    _send_msg(conn, ("ok", None))
                elif op == "get_states":
                    with self._mutate:
                        blob = (self._updater.get_states(payload)
                                if self._updater is not None else None)
                    _send_msg(conn, ("ok", blob))
                elif op == "set_states":
                    with self._mutate:
                        if self._updater is None:
                            _send_msg(conn, ("err",
                                             "no server-side optimizer"))
                            continue
                        self._updater.set_states(payload)
                    _send_msg(conn, ("ok", None))
                else:
                    _send_msg(conn, ("err", "unknown op %r" % op))
        except (ConnectionError, EOFError):
            pass
        finally:
            conn.close()

    def close(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


class AsyncClient:
    """One worker's connection to the async server."""

    def __init__(self, host, port, timeout=30.0):
        import time

        deadline = time.monotonic() + timeout
        last = None
        while time.monotonic() < deadline:
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=timeout)
                # connect-only timeout: a push ack can legitimately wait
                # behind other workers applying serially; a recv timeout
                # mid-frame would desync the length-prefixed protocol
                self._sock.settimeout(None)
                break
            except OSError as e:  # server thread may not be up yet
                last = e
                time.sleep(0.2)
        else:
            raise MXNetError(
                "cannot reach async kvstore server at %s:%d (%r)"
                % (host, port, last))
        self._lock = threading.Lock()

    def request(self, op, key=None, payload=None):
        with self._lock:
            _send_msg(self._sock, (op, key, payload))
            status, result = _recv_msg(self._sock)
        if status != "ok":
            raise MXNetError("async kvstore server error: %s" % result)
        return result

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass
