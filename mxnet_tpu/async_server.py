"""Asynchronous parameter server — the ps-lite/hogwild analog
(ref: src/kvstore/kvstore_dist_server.h — KVStoreDistServer::DataHandleEx
async branch: each worker's push is applied to the server-side weight the
moment it arrives, with NO cross-worker barrier; pulls return whatever
the weight currently is).

TPU-native placement note: synchronous data parallelism compiles into the
training step as XLA collectives (parallel/sharded.py) — that path never
touches this module. True ASYNC semantics cannot ride collectives (they
are barriers by construction), so dist_async gets what the reference has:
a parameter-server process. Here it is a thread inside worker 0 speaking
length-prefixed pickles over TCP; the server address derives from the
launcher's coordinator (MXT_COORDINATOR host, port + ASYNC_PORT_OFFSET).

Asynchrony is BETWEEN WORKERS: no worker ever waits for another's push
(the reference's async contract). Application at the server is
serialized by a store lock, matching ps-lite's per-server customer
thread, which handles one message at a time — "lock-free" in the
reference describes the absence of worker-side barriers, not racy
read-modify-write on the server. A push is fully applied before its
ack, so each worker's own pushes are totally ordered.

Trust boundary / threat model
-----------------------------
Frames are pickled Python objects: deserializing one executes arbitrary
code chosen by the sender, so the wire protocol authenticates WHO may
speak, not what they say (same posture as ps-lite's ``Van``, which had a
membership protocol but no payload sandbox — any admitted node is fully
trusted). Enforcement:

- Without a shared secret the server refuses to bind anything but
  loopback — single-host rigs work out of the box, and nothing pickled
  ever arrives off-box.
- For multi-host (``MXT_COORDINATOR`` set), set ``MXT_KVSTORE_SECRET``
  on every node (the launcher forwards it): each frame then carries an
  HMAC-SHA256 over (connection nonce ‖ direction ‖ sequence ‖ payload),
  verified BEFORE unpickling. The per-connection server nonce plus a
  per-direction sequence counter defeats cross-connection replay and
  reflection; a missing or wrong MAC drops the connection. The secret
  gates membership — anyone holding it has remote-execution-equivalent
  trust, exactly like a reference cluster's network perimeter.
- Every accepted connection starts with a server banner announcing
  whether auth is required, so a secret-presence mismatch between peers
  is a clean error, not a protocol desync.
- TLS/on-wire privacy is out of scope (the reference has none either);
  run on a trusted network segment.

Membership (ps-lite ``Van`` analog, see membership.py): the server keeps
a MembershipTable — register/heartbeat/deregister ops, a reaper thread
that fences workers after ``MXT_LIVENESS_TIMEOUT`` silent seconds, and
elastic barrier/reduce rendezvous that release over LIVE members. Data
frames may carry a (worker_id, generation) credential; a fenced
generation gets a typed ``stale`` reply (→ StaleWorkerError) so zombies
can never corrupt the store. The banner carries a per-instance boot id
so a reconnecting client detects a server restart and resyncs.
"""
from __future__ import annotations

import hmac
import hashlib
import os
import pickle
import socket
import struct
import threading
import time

from . import telemetry
from .base import MXNetError
from .membership import (BarrierTimeout, MembershipTable, StaleWorkerError,
                         snapshot_checksums)

ASYNC_PORT_OFFSET = 1717

__all__ = ["AsyncParamServer", "AsyncClient", "server_address",
           "get_server", "ASYNC_PORT_OFFSET"]

_SERVERS = {}  # (host, port) -> AsyncParamServer (one bind per process)


def get_server(host, port):
    """Process-wide server singleton: re-creating a dist_async KVStore
    must not re-bind the port (EADDRINUSE); a new store generation
    RESETs the existing server instead."""
    key = (host, port)
    if key not in _SERVERS:
        _SERVERS[key] = AsyncParamServer(host, port)
    return _SERVERS[key]


def server_address():
    """host:port of the async server, derived from MXT_COORDINATOR."""
    coord = os.environ.get("MXT_COORDINATOR")
    if not coord or ":" not in coord:
        return None
    host, _, port = coord.rpartition(":")
    return host, int(port) + ASYNC_PORT_OFFSET


_MAC_LEN = hashlib.sha256().digest_size
_BANNER_MAGIC = b"MXKV"
_NONCE_LEN = 16
_BOOT_ID_LEN = 8

# data ops that mutate server-side state: with membership active (any
# registered member + MXT_MEMBERSHIP on) these REQUIRE a live credential,
# so a restarted-but-unregistered worker cannot corrupt weights. 'reset'
# is exempt: it is the coordinated whole-world restart issued from inside
# KVStore.create() before the new world's members have registered.
# The emb_* entries extend the fence to row-granular sparse pushes on
# the embedding store (embedding/store.py) — a fenced zombie's delayed
# gradient ROWS are refused exactly like its dense frames.
_FENCED_OPS = frozenset(("init", "push", "set_optimizer", "set_states",
                         "emb_init", "emb_init_lazy", "emb_load",
                         "emb_push", "emb_set_optimizer"))


def _shared_secret():
    """Frame-auth key from the environment (launcher forwards it to every
    node). None → unauthenticated frames, loopback-only enforcement."""
    s = os.environ.get("MXT_KVSTORE_SECRET")
    return s.encode("utf-8") if s else None


def _is_loopback(host):
    # NB: "" binds INADDR_ANY — it is NOT loopback
    return host in ("127.0.0.1", "::1", "localhost")


class _Channel:
    """One authenticated (or plain) connection endpoint.

    The server opens each accepted connection with a banner
    ``MXKV | flags | nonce?`` (flags bit0: auth required) so both sides
    agree on framing before any frame flows. With auth, each direction
    MACs ``nonce ‖ dir ‖ seq ‖ payload`` with its own monotone sequence
    counter — a frame cannot be replayed on another connection (different
    nonce), re-ordered/re-sent within one (seq), or reflected back (dir).
    """

    def __init__(self, sock, secret, nonce, direction):
        self._sock = sock
        self._secret = secret
        self._nonce = nonce
        self._send_dir = direction
        self._recv_dir = b"S" if direction == b"C" else b"C"
        self._send_seq = 0
        self._recv_seq = 0
        # payload sizes of the newest frame each way: the telemetry RPC
        # bytes histograms read these (the channel is the only place
        # that knows the pickled size)
        self.last_send_len = 0
        self.last_recv_len = 0

    def _mac(self, direction, seq, payload):
        msg = self._nonce + direction + struct.pack("!Q", seq) + payload
        return hmac.new(self._secret, msg, hashlib.sha256).digest()

    def send(self, obj):
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        self.last_send_len = len(payload)
        if self._secret is not None:
            mac = self._mac(self._send_dir, self._send_seq, payload)
            self._send_seq += 1
            self._sock.sendall(struct.pack("!Q", len(payload)) + mac +
                               payload)
        else:
            self._sock.sendall(struct.pack("!Q", len(payload)) + payload)

    def recv(self):
        (n,) = struct.unpack("!Q", _recv_exact(self._sock, 8))
        self.last_recv_len = n
        if self._secret is not None:
            mac = _recv_exact(self._sock, _MAC_LEN)
            payload = _recv_exact(self._sock, n)
            want = self._mac(self._recv_dir, self._recv_seq, payload)
            if not hmac.compare_digest(mac, want):
                # authenticate BEFORE deserializing — a tampered, replayed
                # or mis-keyed frame must never reach pickle.loads
                raise MXNetError(
                    "async kvstore frame failed HMAC verification "
                    "(tampered/replayed, or MXT_KVSTORE_SECRET mismatch)")
            self._recv_seq += 1
            return pickle.loads(payload)
        return pickle.loads(_recv_exact(self._sock, n))


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("async kvstore peer closed")
        buf += chunk
    return buf


class AsyncParamServer:
    """Threaded TCP server holding weights + the server-side optimizer."""

    def __init__(self, host, port):
        if not _is_loopback(host) and _shared_secret() is None:
            raise MXNetError(
                "refusing to bind the async kvstore server to %r without "
                "frame authentication: frames are pickles (deserializing "
                "one is code execution). Set MXT_KVSTORE_SECRET on every "
                "node for multi-host, or bind loopback." % host)
        self._secret = _shared_secret()  # auth mode fixed at bind time
        self._store = {}     # key -> np.ndarray (the weight)
        self._updater = None
        self.embedding = None  # EmbeddingStore (attach_embedding)
        self.serving = None    # ServingHost (attach_serving)
        self.data_plane = None  # ChunkLedger (attach_data_plane)
        self._mutate = threading.Lock()  # ps-lite customer-thread analog
        self._conns = set()  # live client sockets, torn down by close()
        self._conns_lock = threading.Lock()
        # boot id: lets a reconnecting client detect that the server it
        # reached is a RESTARTED instance (empty store, empty membership)
        # rather than the one it handshook with — the banner carries it
        self.boot_id = os.urandom(_BOOT_ID_LEN)
        # membership view (ps-lite Van analog): registrations, heartbeat
        # stamps, generation fencing, and the elastic barrier/reduce
        # rendezvous all live here; the reaper thread declares workers
        # dead after MXT_LIVENESS_TIMEOUT seconds of silence
        self.membership = MembershipTable()
        self._world = 0  # reset count: store-generation rendezvous token
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._sock.bind((host, port))
        except OSError:
            # leave no half-open socket behind: the caller may fall
            # back to client-only mode against whoever owns the port
            # (standalone kvstore_server hosting the coordinator)
            self._sock.close()
            raise
        self._sock.listen(64)
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="kv-async-accept")
        self._accept_thread.start()
        self._reap_thread = threading.Thread(
            target=self._reap_loop, daemon=True, name="kv-member-reaper")
        self._reap_thread.start()

    def _reap_loop(self):
        """Declare silent workers dead (config read per tick so tests can
        shrink the windows on the process-wide server singleton)."""
        from . import config

        while not self._stop.is_set():
            interval = float(config.get("MXT_HEARTBEAT_INTERVAL"))
            timeout = float(config.get("MXT_LIVENESS_TIMEOUT"))
            self.membership.reap(timeout)
            self._stop.wait(max(0.01, min(interval / 2.0, 0.5)))

    # -- server side -------------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed during shutdown
            if self._stop.is_set():
                # a connect that raced close(): refuse service
                conn.close()
                return
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True, name="kv-async-conn").start()

    def _serve(self, conn):
        # banner: announce auth mode + this instance's boot id (+ a
        # per-connection nonce when auth is on) so a secret-presence
        # mismatch fails loudly and a reconnecting client can detect a
        # server RESTART (different boot id → resync, not silent reuse)
        secret = self._secret
        flags = 1 if secret is not None else 0
        nonce = os.urandom(_NONCE_LEN) if secret is not None else b""
        try:
            conn.sendall(_BANNER_MAGIC + bytes([flags]) + self.boot_id +
                         nonce)
        except OSError:
            conn.close()
            return
        ch = _Channel(conn, secret, nonce, b"S")
        try:
            while True:
                try:
                    frame = ch.recv()
                except MXNetError:
                    # auth failure: drop without answering (an
                    # unauthenticated peer learns nothing); errors AFTER
                    # auth go back as ("err", ...) frames below
                    return
                trace = None
                if len(frame) == 5:
                    # traced frame: (trace_id, span_id, attempt) rides
                    # the header so every push/pull/heartbeat/rendezvous
                    # is correlatable with the worker that sent it
                    op, key, payload, cred, trace = frame
                elif len(frame) == 4:
                    # membership-credentialed frame (worker_id, generation)
                    op, key, payload, cred = frame
                else:
                    (op, key, payload), cred = frame, None
                if isinstance(key, str) and key.isdigit():
                    # the eager updater keys optimizer state and lr/wd
                    # multipliers by int for digit keys (kvstore.py push)
                    key = int(key)
                nbytes = ch.last_recv_len
                t0 = time.perf_counter()
                try:
                    reply = self._handle(op, key, payload, cred)
                except StaleWorkerError as e:
                    # fenced frame: refused, but the connection stays up
                    # (the client raises a typed error; a rejoin may
                    # follow on the same socket)
                    reply = ("stale", str(e))
                except BarrierTimeout as e:
                    reply = ("timeout", str(e))
                telemetry.record_rpc(
                    "server", op, seconds=time.perf_counter() - t0,
                    nbytes=nbytes, trace=trace, key=key,
                    status=reply[0] if isinstance(reply, tuple) and reply
                    else "ok")
                ch.send(reply)
        except (OSError, EOFError):
            # includes EBADF from close() tearing the socket out from
            # under a handler blocked in recv (server shutdown/bounce)
            pass
        except MXNetError as e:
            # post-auth handler failure (bad optimizer config, shape
            # mismatch in an update): report it to the worker instead of
            # a bare EOF. (Auth failures return early above, unanswered.)
            try:
                ch.send(("err", "server error: %s" % e))
            except OSError:
                pass
        finally:
            conn.close()
            with self._conns_lock:
                self._conns.discard(conn)

    def attach_embedding(self, store):
        """Host a sharded embedding table store on this server: every
        ``emb_*`` frame dispatches to it (embedding/store.py), under the
        same membership credential fencing as the dense ops."""
        self.embedding = store
        return store

    def attach_serving(self, host):
        """Host a standalone serving replica's front door on this
        server: every ``srv_*`` frame (submit/cancel/poll/load/drain,
        plus the disaggregation pair ship_pages/adopt_pages that moves
        finished prefill KV pages between replicas — serving/fleet.py
        ServingHost) dispatches to it. Serving ops carry no membership
        credential — the fencing that matters for the fleet is
        router-side (a fenced replica's late reply is refused typed at
        the accept gate)."""
        self.serving = host
        return host

    def attach_data_plane(self, ledger):
        """Host the streaming data plane's chunk lease ledger on this
        server: every ``data_*`` frame (lease/steal/cursor —
        data_plane/ledger.py) dispatches to it. The membership reaper
        feeds it: a reaped worker's host id is fenced in the ledger, so
        its unconsumed chunks become stealable by survivors and its
        zombie commits are refused typed (the lease-generation fence —
        PR 10's ring-epoch discipline applied to input)."""
        self.data_plane = ledger
        self.membership.add_death_listener(
            lambda ids: [ledger.fence_host(i) for i in ids
                         if isinstance(i, int) and i >= 0])
        return ledger

    def _fencing_active(self):
        from . import config

        return bool(config.get("MXT_MEMBERSHIP")) \
            and self.membership.has_members()

    def _handle(self, op, key, payload, cred):
        """One request → one reply tuple. StaleWorkerError/BarrierTimeout
        propagate to _serve, which answers without dropping the
        connection."""
        from .ndarray.ndarray import NDArray
        import numpy as np
        import jax.numpy as jnp

        # stale-push fencing: a credentialed frame must come from the
        # current LIVE incarnation of its worker; with membership active,
        # mutating the store additionally requires a credential, so a
        # restarted-but-unregistered worker can never corrupt weights
        if cred is not None:
            self.membership.check(cred[0], cred[1])
        elif op in _FENCED_OPS and self._fencing_active():
            raise StaleWorkerError(
                "%r from an unregistered connection while membership is "
                "active — register (or rejoin) before mutating server "
                "state" % op)

        if op == "reset":
            with self._mutate:
                self._store.clear()
                self._updater = None
                self._world += 1
                world = self._world
            # new store world: members must re-register (the generation
            # counter survives, so pre-reset credentials stay fenced)
            self.membership.reset()
            return ("ok", world)
        elif op == "world":
            # store-generation rendezvous: workers wait for rank 0's Nth
            # reset before touching world N (replaces the jax collective
            # barrier that used to guard creation — no XLA dependency)
            with self._mutate:
                return ("ok", self._world)
        elif op == "init":
            with self._mutate:
                # first writer wins (every worker sends its init)
                self._store.setdefault(key, np.array(payload))
            return ("ok", None)
        elif op == "push":
            with self._mutate:
                w = self._store.get(key)
                if w is None:
                    # first push initializes, like KVStoreLocal
                    self._store[key] = np.array(payload)
                    return ("ok", None)
                if self._updater is not None:
                    w_nd = NDArray(jnp.asarray(w))
                    self._updater(key,
                                  NDArray(jnp.asarray(payload)),
                                  w_nd)
                    self._store[key] = np.asarray(w_nd.data)
                else:
                    # replace semantics, matching the local
                    # no-updater path (CopyFromTo(merged, &local))
                    self._store[key] = np.array(payload)
            return ("ok", None)
        elif op == "pull":
            w = self._store.get(key)
            if w is None:
                return ("err", "key %r not initialized" % key)
            return ("ok", np.array(w))
        elif op == "set_optimizer":
            from . import optimizer as opt

            with self._mutate:
                self._updater = opt.get_updater(pickle.loads(payload))
            return ("ok", None)
        elif op == "get_states":
            with self._mutate:
                blob = (self._updater.get_states(payload)
                        if self._updater is not None else None)
            return ("ok", blob)
        elif op == "set_states":
            with self._mutate:
                if self._updater is None:
                    return ("err", "no server-side optimizer")
                self._updater.set_states(payload)
            return ("ok", None)
        # -- sharded embedding store (embedding/store.py) -----------------
        elif op.startswith("emb_"):
            if self.embedding is None:
                return ("err", "this server hosts no embedding store "
                               "(attach_embedding / kvstore_server)")
            # credential fencing already ran above; the store adds the
            # row-granular ring-epoch fence for mutations
            return self.embedding.handle(op, key, payload)
        # -- streaming data plane lease ledger (data_plane/ledger.py) -----
        elif op.startswith("data_"):
            if self.data_plane is None:
                return ("err", "this server hosts no data-plane ledger "
                               "(attach_data_plane)")
            # a stale lease generation raises StaleLeaseError (a
            # StaleWorkerError) — _serve answers it as a typed 'stale'
            # reply, exactly like a fenced worker's dense push
            return self.data_plane.handle(op, key, payload)
        # -- standalone serving replica (serving/fleet.py) ----------------
        elif op.startswith("srv_"):
            if self.serving is None:
                return ("err", "this server hosts no serving replica "
                               "(attach_serving / serving.serve_replica)")
            return self.serving.handle(op, key, payload)
        # -- fleet telemetry scrape (telemetry_fleet.py collector) --------
        elif op == "tel_snapshot":
            # this process's whole metrics registry as a serializable
            # snapshot — read-only, unfenced (scraping must work even
            # while membership churns), pure host data
            return ("ok", telemetry.registry_export())
        elif op == "tel_spans":
            # the bounded request-trace span log (optionally filtered
            # to one trace_id carried in the payload)
            return ("ok", telemetry.trace_spans(payload))
        # -- membership ops (ref: ps-lite Van ADD_NODE/HEARTBEAT) --------
        elif op == "register":
            meta = None
            if len(payload) == 3:
                worker_id, want_snapshot, meta = payload
            else:
                worker_id, want_snapshot = payload
            gen, epoch, rejoin = self.membership.register(worker_id,
                                                          meta=meta)
            from . import resilience

            inj = resilience.fault_point()
            if inj.should("rejoin_race"):
                # widen the window between fencing the old generation
                # and answering the rejoin: a zombie push racing the
                # re-registration must STILL be refused in here
                time.sleep(
                    float(inj.rule("rejoin_race").get("ms", 20.0)) / 1e3)
            snap = None
            if want_snapshot or rejoin:
                # rejoin handoff: the current store + optimizer states
                # under a CRC32 manifest (the wire analog of
                # CheckpointManager's per-file CRCs)
                with self._mutate:
                    weights = {k: np.array(v)
                               for k, v in self._store.items()}
                    states = (self._updater.get_states(False)
                              if self._updater is not None else None)
                snap = {"weights": weights, "states": states,
                        "epoch": epoch,
                        # last released barrier/reduce rounds: a
                        # rejoined worker resumes at the SURVIVORS'
                        # sequence numbers instead of restarting at 0
                        # (fresh counters would never match their
                        # rounds and every rendezvous would time out)
                        "seqs": self.membership.rendezvous_seqs(),
                        "crc32": snapshot_checksums(weights)}
            return ("ok", (gen, epoch, snap))
        elif op == "heartbeat":
            worker_id, gen = payload
            epoch, lost = self.membership.heartbeat(worker_id, gen)
            return ("ok", (epoch, lost))
        elif op == "deregister":
            worker_id, gen = payload
            self.membership.deregister(worker_id, gen)
            return ("ok", None)
        elif op == "members":
            return ("ok", self.membership.view())
        elif op == "barrier":
            worker_id, gen, tag, timeout = payload
            epoch = self.membership.barrier(worker_id, gen, tag, timeout)
            return ("ok", epoch)
        elif op == "reduce":
            worker_id, gen, seq, array, timeout = payload
            total, wids = self.membership.reduce(
                worker_id, gen, key, seq, np.asarray(array), timeout)
            return ("ok", (total, wids))
        return ("err", "unknown op %r" % op)

    def close(self):
        """Stop serving: wake the (possibly accept()-blocked) listener —
        a blocked accept holds a kernel reference that would otherwise
        keep the port alive — and tear down live client connections, so
        'server gone' is observable by workers (their retries then fail
        over to KVStoreError instead of talking to a zombie)."""
        self._stop.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


class AsyncClient:
    """One worker's connection to the async server.

    ``request`` is fault-tolerant: a connection-shaped failure (peer
    reset, injected ``MXT_FAULT`` drop) tears the socket down and
    reconnects — full banner handshake included — under the
    resilience retry policy (exponential backoff + jitter, bounded
    retries, per-op deadline). A server that is truly gone raises
    :class:`~..resilience.KVStoreError` instead of hanging. Delivery is
    at-least-once: a drop in the window between the server applying a
    push and its ack being read re-sends the push (the reference's
    hogwild async mode tolerates duplicate gradient application the same
    way it tolerates staleness)."""

    def __init__(self, host, port, timeout=30.0):
        self._host = host
        self._port = port
        self._timeout = timeout
        self._lock = threading.Lock()
        self._cred = None        # (worker_id, generation) membership token
        self._boot_id = None     # server instance id from the banner
        self._saw_restart = False
        self._needs_resync = False  # restarted server, state NOT restored
        self.server_restarts = 0
        # resync hook: invoked (with this client) after a reconnect that
        # landed on a RESTARTED server instance — the kvstore wires this
        # to membership re-registration so pushes are not stale-fenced
        # against the new server's empty membership table
        self.on_server_restart = None
        self._connect()

    def set_credentials(self, worker_id, generation):
        """Attach the membership fencing token: every subsequent frame
        carries (worker_id, generation) and the server refuses it once
        the generation is fenced (StaleWorkerError). Fresh credentials
        are the caller's acknowledgment of the current server world, so
        this also clears the restarted-server mutation fence."""
        self._cred = (int(worker_id), int(generation))
        self._needs_resync = False

    def _connect(self):
        import time

        host, port, timeout = self._host, self._port, self._timeout
        deadline = time.monotonic() + timeout
        last = None
        while time.monotonic() < deadline:
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=timeout)
                # connect-only timeout: a push ack can legitimately wait
                # behind other workers applying serially; a recv timeout
                # mid-frame would desync the length-prefixed protocol
                self._sock.settimeout(None)
                break
            except OSError as e:  # server thread may not be up yet
                last = e
                time.sleep(0.2)
        else:
            raise MXNetError(
                "cannot reach async kvstore server at %s:%d (%r)"
                % (host, port, last))
        # server banner: agree on the auth mode before any frame flows.
        # Time-bounded (a bannerless pre-r5 peer sends nothing and would
        # hang us) and the socket is closed on any handshake failure.
        try:
            self._sock.settimeout(timeout)
            head = _recv_exact(self._sock,
                               len(_BANNER_MAGIC) + 1 + _BOOT_ID_LEN)
            if head[:len(_BANNER_MAGIC)] != _BANNER_MAGIC:
                raise MXNetError(
                    "peer at %s:%d did not send an async kvstore banner "
                    "(not an async server, or a pre-r5 build)"
                    % (host, port))
            server_auth = bool(head[len(_BANNER_MAGIC)] & 1)
            boot_id = head[len(_BANNER_MAGIC) + 1:]
            # a different boot id on reconnect = the server RESTARTED
            # mid-run (fresh store, fresh membership): flag it so the
            # resync hook runs instead of silently reusing stale
            # expectations against the new instance
            if self._boot_id is not None and boot_id != self._boot_id:
                self._saw_restart = True
            self._boot_id = boot_id
            secret = _shared_secret()
            if server_auth and secret is None:
                raise MXNetError(
                    "async kvstore server requires frame authentication "
                    "but MXT_KVSTORE_SECRET is not set on this worker")
            if not server_auth and secret is not None:
                raise MXNetError(
                    "MXT_KVSTORE_SECRET is set on this worker but the "
                    "server does not authenticate frames — refusing the "
                    "downgrade")
            nonce = _recv_exact(self._sock, _NONCE_LEN) if server_auth \
                else b""
            self._sock.settimeout(None)
        except (OSError, MXNetError, ConnectionError) as e:
            self._sock.close()
            if isinstance(e, socket.timeout):
                raise MXNetError(
                    "timed out waiting for the async kvstore banner from "
                    "%s:%d (not an async server, or a pre-r5 build)"
                    % (host, port)) from e
            raise
        self._ch = _Channel(self._sock, secret if server_auth else None,
                            nonce, b"C")

    def _reconnect(self):
        try:
            self._sock.close()
        except OSError:
            pass
        self._connect()
        if self._saw_restart:
            self._saw_restart = False
            self.server_restarts += 1
            cb = self.on_server_restart
            if cb is not None:
                # resync (e.g. membership re-registration) BEFORE the
                # retried frame is re-sent — it picks up new credentials
                cb(self)
            else:
                # nobody restored the restarted instance's (empty)
                # store and optimizer: fence mutating ops until the
                # owner resyncs (set_credentials after an explicit
                # re-registration clears it). Reads stay open — pulls
                # against the empty store are typed errors, and a
                # rejoin needs register/heartbeat to pass.
                self._needs_resync = True

    def request(self, op, key=None, payload=None, deadline=None):
        """One op round-trip under the retry policy. ``deadline``
        overrides the per-op retry deadline AND puts a recv timeout on
        the socket for this request — rendezvous ops (barrier/reduce)
        pass their rendezvous timeout plus a margin so the server's
        typed release/timeout reply wins the race against the transport
        giving up (a premature client retry would park a duplicate
        waiter server-side)."""
        from . import resilience
        from .membership import StaleWorkerError
        from .resilience import KVStoreError

        # one trace per logical request (the ambient trace_scope id when
        # a caller installed one); each ATTEMPT gets its own span id and
        # attempt number, so retries are visible server-side
        trace_id = telemetry.current_trace_id() or telemetry.new_trace_id()
        attempt_no = [-1]

        def attempt():
            attempt_no[0] += 1
            trace = (trace_id, telemetry.new_span_id(), attempt_no[0])
            t0 = time.perf_counter()
            with self._lock:
                if self._needs_resync and op in _FENCED_OPS:
                    raise KVStoreError(
                        "async kvstore server RESTARTED mid-run (boot id "
                        "changed) and its store/optimizer were not "
                        "restored — refusing %r: a retried push against "
                        "the empty store would install a raw gradient "
                        "as the weight. Re-register (rejoin) and re-seed "
                        "server state, then set_credentials." % (op,))
                if deadline is not None:
                    self._sock.settimeout(float(deadline))
                try:
                    # frame built per attempt so a resync hook's
                    # refreshed credentials apply to the retried send
                    self._ch.send((op, key, payload, self._cred, trace))
                    reply = self._ch.recv()
                    nbytes = self._ch.last_send_len
                finally:
                    if deadline is not None:
                        try:
                            self._sock.settimeout(None)
                        except OSError:
                            pass
            telemetry.record_rpc(
                "client", op, seconds=time.perf_counter() - t0,
                nbytes=nbytes, trace=trace, key=key,
                status=reply[0] if isinstance(reply, tuple) and reply
                else "ok")
            return reply

        policy = None
        if deadline is not None:
            policy = resilience.RetryPolicy.from_config()
            policy.deadline = float(deadline)
        # the hang watchdog observes RPC completions: a request blocked
        # past MXT_WATCHDOG_TIMEOUT shows as kvstore_rpc pending > 0
        # with a frozen completion counter (pure host bookkeeping)
        from . import diagnostics

        with diagnostics.pending_scope("kvstore_rpc"):
            status, result = resilience.kv_retry(
                op, key, attempt, reconnect=self._reconnect, policy=policy)
        diagnostics.progress("kvstore_rpc")
        if status == "stale":
            raise StaleWorkerError(result)
        if status == "timeout":
            raise KVStoreError(result)
        if status != "ok":
            raise MXNetError("async kvstore server error: %s" % result)
        return result

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass
