"""Asynchronous parameter server — the ps-lite/hogwild analog
(ref: src/kvstore/kvstore_dist_server.h — KVStoreDistServer::DataHandleEx
async branch: each worker's push is applied to the server-side weight the
moment it arrives, with NO cross-worker barrier; pulls return whatever
the weight currently is).

TPU-native placement note: synchronous data parallelism compiles into the
training step as XLA collectives (parallel/sharded.py) — that path never
touches this module. True ASYNC semantics cannot ride collectives (they
are barriers by construction), so dist_async gets what the reference has:
a parameter-server process. Here it is a thread inside worker 0 speaking
length-prefixed pickles over TCP; the server address derives from the
launcher's coordinator (MXT_COORDINATOR host, port + ASYNC_PORT_OFFSET).

Asynchrony is BETWEEN WORKERS: no worker ever waits for another's push
(the reference's async contract). Application at the server is
serialized by a store lock, matching ps-lite's per-server customer
thread, which handles one message at a time — "lock-free" in the
reference describes the absence of worker-side barriers, not racy
read-modify-write on the server. A push is fully applied before its
ack, so each worker's own pushes are totally ordered.

Trust boundary / threat model
-----------------------------
Frames are pickled Python objects: deserializing one executes arbitrary
code chosen by the sender, so the wire protocol authenticates WHO may
speak, not what they say (same posture as ps-lite's ``Van``, which had a
membership protocol but no payload sandbox — any admitted node is fully
trusted). Enforcement:

- Without a shared secret the server refuses to bind anything but
  loopback — single-host rigs work out of the box, and nothing pickled
  ever arrives off-box.
- For multi-host (``MXT_COORDINATOR`` set), set ``MXT_KVSTORE_SECRET``
  on every node (the launcher forwards it): each frame then carries an
  HMAC-SHA256 over (connection nonce ‖ direction ‖ sequence ‖ payload),
  verified BEFORE unpickling. The per-connection server nonce plus a
  per-direction sequence counter defeats cross-connection replay and
  reflection; a missing or wrong MAC drops the connection. The secret
  gates membership — anyone holding it has remote-execution-equivalent
  trust, exactly like a reference cluster's network perimeter.
- Every accepted connection starts with a server banner announcing
  whether auth is required, so a secret-presence mismatch between peers
  is a clean error, not a protocol desync.
- TLS/on-wire privacy is out of scope (the reference has none either);
  run on a trusted network segment.
"""
from __future__ import annotations

import hmac
import hashlib
import os
import pickle
import socket
import struct
import threading

from .base import MXNetError

ASYNC_PORT_OFFSET = 1717

__all__ = ["AsyncParamServer", "AsyncClient", "server_address",
           "get_server", "ASYNC_PORT_OFFSET"]

_SERVERS = {}  # (host, port) -> AsyncParamServer (one bind per process)


def get_server(host, port):
    """Process-wide server singleton: re-creating a dist_async KVStore
    must not re-bind the port (EADDRINUSE); a new store generation
    RESETs the existing server instead."""
    key = (host, port)
    if key not in _SERVERS:
        _SERVERS[key] = AsyncParamServer(host, port)
    return _SERVERS[key]


def server_address():
    """host:port of the async server, derived from MXT_COORDINATOR."""
    coord = os.environ.get("MXT_COORDINATOR")
    if not coord or ":" not in coord:
        return None
    host, _, port = coord.rpartition(":")
    return host, int(port) + ASYNC_PORT_OFFSET


_MAC_LEN = hashlib.sha256().digest_size
_BANNER_MAGIC = b"MXKV"
_NONCE_LEN = 16


def _shared_secret():
    """Frame-auth key from the environment (launcher forwards it to every
    node). None → unauthenticated frames, loopback-only enforcement."""
    s = os.environ.get("MXT_KVSTORE_SECRET")
    return s.encode("utf-8") if s else None


def _is_loopback(host):
    # NB: "" binds INADDR_ANY — it is NOT loopback
    return host in ("127.0.0.1", "::1", "localhost")


class _Channel:
    """One authenticated (or plain) connection endpoint.

    The server opens each accepted connection with a banner
    ``MXKV | flags | nonce?`` (flags bit0: auth required) so both sides
    agree on framing before any frame flows. With auth, each direction
    MACs ``nonce ‖ dir ‖ seq ‖ payload`` with its own monotone sequence
    counter — a frame cannot be replayed on another connection (different
    nonce), re-ordered/re-sent within one (seq), or reflected back (dir).
    """

    def __init__(self, sock, secret, nonce, direction):
        self._sock = sock
        self._secret = secret
        self._nonce = nonce
        self._send_dir = direction
        self._recv_dir = b"S" if direction == b"C" else b"C"
        self._send_seq = 0
        self._recv_seq = 0

    def _mac(self, direction, seq, payload):
        msg = self._nonce + direction + struct.pack("!Q", seq) + payload
        return hmac.new(self._secret, msg, hashlib.sha256).digest()

    def send(self, obj):
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        if self._secret is not None:
            mac = self._mac(self._send_dir, self._send_seq, payload)
            self._send_seq += 1
            self._sock.sendall(struct.pack("!Q", len(payload)) + mac +
                               payload)
        else:
            self._sock.sendall(struct.pack("!Q", len(payload)) + payload)

    def recv(self):
        (n,) = struct.unpack("!Q", _recv_exact(self._sock, 8))
        if self._secret is not None:
            mac = _recv_exact(self._sock, _MAC_LEN)
            payload = _recv_exact(self._sock, n)
            want = self._mac(self._recv_dir, self._recv_seq, payload)
            if not hmac.compare_digest(mac, want):
                # authenticate BEFORE deserializing — a tampered, replayed
                # or mis-keyed frame must never reach pickle.loads
                raise MXNetError(
                    "async kvstore frame failed HMAC verification "
                    "(tampered/replayed, or MXT_KVSTORE_SECRET mismatch)")
            self._recv_seq += 1
            return pickle.loads(payload)
        return pickle.loads(_recv_exact(self._sock, n))


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("async kvstore peer closed")
        buf += chunk
    return buf


class AsyncParamServer:
    """Threaded TCP server holding weights + the server-side optimizer."""

    def __init__(self, host, port):
        if not _is_loopback(host) and _shared_secret() is None:
            raise MXNetError(
                "refusing to bind the async kvstore server to %r without "
                "frame authentication: frames are pickles (deserializing "
                "one is code execution). Set MXT_KVSTORE_SECRET on every "
                "node for multi-host, or bind loopback." % host)
        self._secret = _shared_secret()  # auth mode fixed at bind time
        self._store = {}     # key -> np.ndarray (the weight)
        self._updater = None
        self._mutate = threading.Lock()  # ps-lite customer-thread analog
        self._conns = set()  # live client sockets, torn down by close()
        self._conns_lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="kv-async-accept")
        self._accept_thread.start()

    # -- server side -------------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed during shutdown
            if self._stop.is_set():
                # a connect that raced close(): refuse service
                conn.close()
                return
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True, name="kv-async-conn").start()

    def _serve(self, conn):
        from .ndarray.ndarray import NDArray
        import numpy as np
        import jax.numpy as jnp

        # banner: announce auth mode (+ per-connection nonce when on) so
        # a secret-presence mismatch fails loudly instead of desyncing
        secret = self._secret
        flags = 1 if secret is not None else 0
        nonce = os.urandom(_NONCE_LEN) if secret is not None else b""
        try:
            conn.sendall(_BANNER_MAGIC + bytes([flags]) + nonce)
        except OSError:
            conn.close()
            return
        ch = _Channel(conn, secret, nonce, b"S")

        def _recv_frame():
            return ch.recv()

        _send_msg = ch.send
        try:
            while True:
                try:
                    op, key, payload = _recv_frame()
                except MXNetError:
                    # auth failure: drop without answering (an
                    # unauthenticated peer learns nothing); errors AFTER
                    # auth go back as ("err", ...) frames below
                    return
                if isinstance(key, str) and key.isdigit():
                    # the eager updater keys optimizer state and lr/wd
                    # multipliers by int for digit keys (kvstore.py push)
                    key = int(key)
                if op == "reset":
                    with self._mutate:
                        self._store.clear()
                        self._updater = None
                    _send_msg(("ok", None))
                elif op == "init":
                    with self._mutate:
                        # first writer wins (every worker sends its init)
                        self._store.setdefault(key, np.array(payload))
                    _send_msg(("ok", None))
                elif op == "push":
                    with self._mutate:
                        w = self._store.get(key)
                        if w is None:
                            # first push initializes, like KVStoreLocal
                            self._store[key] = np.array(payload)
                            _send_msg(("ok", None))
                            continue
                        if self._updater is not None:
                            w_nd = NDArray(jnp.asarray(w))
                            self._updater(key,
                                          NDArray(jnp.asarray(payload)),
                                          w_nd)
                            self._store[key] = np.asarray(w_nd.data)
                        else:
                            # replace semantics, matching the local
                            # no-updater path (CopyFromTo(merged, &local))
                            self._store[key] = np.array(payload)
                    _send_msg(("ok", None))
                elif op == "pull":
                    w = self._store.get(key)
                    if w is None:
                        _send_msg(("err",
                                         "key %r not initialized" % key))
                    else:
                        _send_msg(("ok", np.array(w)))
                elif op == "set_optimizer":
                    from . import optimizer as opt

                    with self._mutate:
                        self._updater = opt.get_updater(
                            pickle.loads(payload))
                    _send_msg(("ok", None))
                elif op == "get_states":
                    with self._mutate:
                        blob = (self._updater.get_states(payload)
                                if self._updater is not None else None)
                    _send_msg(("ok", blob))
                elif op == "set_states":
                    with self._mutate:
                        if self._updater is None:
                            _send_msg(("err",
                                             "no server-side optimizer"))
                            continue
                        self._updater.set_states(payload)
                    _send_msg(("ok", None))
                else:
                    _send_msg(("err", "unknown op %r" % op))
        except (ConnectionError, EOFError):
            pass
        except MXNetError as e:
            # post-auth handler failure (bad optimizer config, shape
            # mismatch in an update): report it to the worker instead of
            # a bare EOF. (Auth failures return early above, unanswered.)
            try:
                _send_msg(("err", "server error: %s" % e))
            except OSError:
                pass
        finally:
            conn.close()
            with self._conns_lock:
                self._conns.discard(conn)

    def close(self):
        """Stop serving: wake the (possibly accept()-blocked) listener —
        a blocked accept holds a kernel reference that would otherwise
        keep the port alive — and tear down live client connections, so
        'server gone' is observable by workers (their retries then fail
        over to KVStoreError instead of talking to a zombie)."""
        self._stop.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


class AsyncClient:
    """One worker's connection to the async server.

    ``request`` is fault-tolerant: a connection-shaped failure (peer
    reset, injected ``MXT_FAULT`` drop) tears the socket down and
    reconnects — full banner handshake included — under the
    resilience retry policy (exponential backoff + jitter, bounded
    retries, per-op deadline). A server that is truly gone raises
    :class:`~..resilience.KVStoreError` instead of hanging. Delivery is
    at-least-once: a drop in the window between the server applying a
    push and its ack being read re-sends the push (the reference's
    hogwild async mode tolerates duplicate gradient application the same
    way it tolerates staleness)."""

    def __init__(self, host, port, timeout=30.0):
        self._host = host
        self._port = port
        self._timeout = timeout
        self._lock = threading.Lock()
        self._connect()

    def _connect(self):
        import time

        host, port, timeout = self._host, self._port, self._timeout
        deadline = time.monotonic() + timeout
        last = None
        while time.monotonic() < deadline:
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=timeout)
                # connect-only timeout: a push ack can legitimately wait
                # behind other workers applying serially; a recv timeout
                # mid-frame would desync the length-prefixed protocol
                self._sock.settimeout(None)
                break
            except OSError as e:  # server thread may not be up yet
                last = e
                time.sleep(0.2)
        else:
            raise MXNetError(
                "cannot reach async kvstore server at %s:%d (%r)"
                % (host, port, last))
        # server banner: agree on the auth mode before any frame flows.
        # Time-bounded (a bannerless pre-r5 peer sends nothing and would
        # hang us) and the socket is closed on any handshake failure.
        try:
            self._sock.settimeout(timeout)
            head = _recv_exact(self._sock, len(_BANNER_MAGIC) + 1)
            if head[:len(_BANNER_MAGIC)] != _BANNER_MAGIC:
                raise MXNetError(
                    "peer at %s:%d did not send an async kvstore banner "
                    "(not an async server, or a pre-r5 build)"
                    % (host, port))
            server_auth = bool(head[len(_BANNER_MAGIC)] & 1)
            secret = _shared_secret()
            if server_auth and secret is None:
                raise MXNetError(
                    "async kvstore server requires frame authentication "
                    "but MXT_KVSTORE_SECRET is not set on this worker")
            if not server_auth and secret is not None:
                raise MXNetError(
                    "MXT_KVSTORE_SECRET is set on this worker but the "
                    "server does not authenticate frames — refusing the "
                    "downgrade")
            nonce = _recv_exact(self._sock, _NONCE_LEN) if server_auth \
                else b""
            self._sock.settimeout(None)
        except (OSError, MXNetError, ConnectionError) as e:
            self._sock.close()
            if isinstance(e, socket.timeout):
                raise MXNetError(
                    "timed out waiting for the async kvstore banner from "
                    "%s:%d (not an async server, or a pre-r5 build)"
                    % (host, port)) from e
            raise
        self._ch = _Channel(self._sock, secret if server_auth else None,
                            nonce, b"C")

    def _reconnect(self):
        try:
            self._sock.close()
        except OSError:
            pass
        self._connect()

    def request(self, op, key=None, payload=None):
        from . import resilience

        def attempt():
            with self._lock:
                self._ch.send((op, key, payload))
                return self._ch.recv()

        status, result = resilience.kv_retry(
            op, key, attempt, reconnect=self._reconnect)
        if status != "ok":
            raise MXNetError("async kvstore server error: %s" % result)
        return result

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass
