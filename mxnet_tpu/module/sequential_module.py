"""SequentialModule — a chain of modules where each consumes the previous
one's outputs (ref: python/mxnet/module/sequential_module.py).

Middle modules are bound with ``inputs_need_grad=True`` so the backward
pass can thread gradients back through the chain (the reference does the
same via META_TAKE_LABELS / data-grad plumbing).
"""
from __future__ import annotations

from ..base import MXNetError
from ..io.io import DataBatch, DataDesc
from .base_module import BaseModule

__all__ = ["SequentialModule"]


class SequentialModule(BaseModule):
    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=None):
        import logging
        super().__init__(logger=logger or logging)
        self._modules = []
        self._metas = []
        self._label_shapes = None
        self._data_shapes = None

    def add(self, module, **kwargs):
        """Append a module; kwargs may include take_labels=True for the
        module that consumes the loss labels (ref: SequentialModule.add)."""
        if self.binded:
            raise MXNetError("cannot add modules after bind()")
        unknown = set(kwargs) - {self.META_TAKE_LABELS,
                                 self.META_AUTO_WIRING}
        if unknown:
            raise MXNetError("unknown meta keys %s" % sorted(unknown))
        self._modules.append(module)
        self._metas.append(dict(kwargs))
        return self

    def __len__(self):
        return len(self._modules)

    @property
    def data_names(self):
        if self._modules:
            return self._modules[0].data_names
        return []

    @property
    def output_names(self):
        if self._modules:
            return self._modules[-1].output_names
        return []

    @property
    def data_shapes(self):
        assert self.binded
        return self._modules[0].data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._modules[-1].output_shapes

    def get_params(self):
        assert self.binded and self.params_initialized
        arg_params = {}
        aux_params = {}
        for mod in self._modules:
            arg, aux = mod.get_params()
            arg_params.update(arg)
            aux_params.update(aux)
        return arg_params, aux_params

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False,
                    force_init=False, allow_extra=False):
        assert self.binded
        # each child sees only its slice of arg_params, so children run
        # permissive and the strictness flags are enforced chain-wide here
        for mod in self._modules:
            mod.init_params(initializer=initializer, arg_params=arg_params,
                            aux_params=aux_params,
                            allow_missing=True, force_init=force_init,
                            allow_extra=True)
        self.params_initialized = True
        if arg_params is None and aux_params is None:
            return
        all_args, all_aux = self.get_params()
        if not allow_missing:
            missing = [n for n in all_args if n not in (arg_params or {})]
            missing += [n for n in all_aux if n not in (aux_params or {})]
            if missing:
                raise MXNetError(
                    "init_params: %s not found in the provided params "
                    "(pass allow_missing=True to initialize them)"
                    % sorted(missing))
        if not allow_extra:
            known = set(all_args) | set(all_aux)
            extra = [n for n in list(arg_params or {})
                     + list(aux_params or {}) if n not in known]
            if extra:
                raise MXNetError(
                    "init_params: provided params %s match no module "
                    "parameter (pass allow_extra=True to ignore)"
                    % sorted(extra))

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        if shared_module is not None:
            raise MXNetError("shared_module is not supported for "
                             "SequentialModule")
        if not self._modules:
            raise MXNetError("add modules before bind()")
        self._label_shapes = label_shapes
        cur_shapes = data_shapes
        for i, (mod, meta) in enumerate(zip(self._modules, self._metas)):
            last = i == len(self._modules) - 1
            labels = label_shapes if meta.get(self.META_TAKE_LABELS) or \
                (last and label_shapes is not None
                 and not any(m.get(self.META_TAKE_LABELS)
                             for m in self._metas)) else None
            # middle modules need input grads so backward can chain —
            # but only when training (inference shouldn't allocate them)
            need_grad = inputs_need_grad if i == 0 else for_training
            mod.bind(cur_shapes, labels, for_training=for_training,
                     inputs_need_grad=need_grad,
                     force_rebind=force_rebind, grad_req=grad_req)
            if not last:
                # output shapes at bind time come from shape inference
                # (Module.output_shapes is only populated after forward)
                if not hasattr(mod, "_symbol"):
                    raise MXNetError(
                        "SequentialModule children must be symbol-backed "
                        "Modules; got %s at position %d (matches "
                        "reference: only Module composes)"
                        % (type(mod).__name__, i))
                known = {d[0] if isinstance(d, tuple) else d.name:
                         d[1] if isinstance(d, tuple) else d.shape
                         for d in cur_shapes}
                _, out_shapes, _ = \
                    mod._symbol.infer_shape_partial(**known)
                nxt = self._modules[i + 1].data_names
                if len(nxt) != len(out_shapes):
                    raise MXNetError(
                        "module %d produces %d outputs but module %d "
                        "expects %d inputs"
                        % (i, len(out_shapes), i + 1, len(nxt)))
                cur_shapes = [DataDesc(n, s)
                              for n, s in zip(nxt, out_shapes)]
        self.binded = True
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        assert self.binded and self.params_initialized
        for mod in self._modules:
            mod.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                               optimizer_params=optimizer_params,
                               force_init=force_init)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        batch = data_batch
        for i, mod in enumerate(self._modules):
            mod.forward(batch, is_train=is_train)
            if i == len(self._modules) - 1:
                break
            batch = DataBatch(data=mod.get_outputs(),
                              label=data_batch.label)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        grads = out_grads
        for i, mod in reversed(list(enumerate(self._modules))):
            mod.backward(out_grads=grads)
            if i == 0:
                break
            grads = mod.get_input_grads()

    def update(self):
        assert self.binded and self.params_initialized
        for mod in self._modules:
            mod.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded
        return self._modules[-1].get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.inputs_need_grad
        return self._modules[0].get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        any_take = False
        for mod, meta in zip(self._modules, self._metas):
            if meta.get(self.META_TAKE_LABELS):
                # every loss-bearing module contributes (a chain can
                # carry an auxiliary loss plus the final head)
                mod.update_metric(eval_metric, labels, pre_sliced)
                any_take = True
        if not any_take:
            self._modules[-1].update_metric(eval_metric, labels,
                                            pre_sliced)

    def install_monitor(self, monitor, monitor_all=False):
        assert self.binded
        for mod in self._modules:
            mod.install_monitor(monitor, monitor_all=monitor_all)
