"""BucketingModule — variable-length sequence training
(ref: python/mxnet/module/bucketing_module.py).

The reference keeps one executor per bucket, all sharing parameter storage.
Here each bucket is a Module whose executor jits at that bucket's shapes —
the jit cache IS the bucket cache (SURVEY §7 hard-part 5: bucket → jit cache
key); parameters are synchronized by sharing the underlying arrays through
copy_params_from on switch.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._work_load_list = work_load_list
        self._fixed_param_names = fixed_param_names
        self._state_names = state_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._grad_req = None
        self._for_training = False
        self._monitor = None
        self._monitor_all = False

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    def _gen_module(self, bucket_key):
        sym, data_names, label_names = self._sym_gen(bucket_key)
        return Module(sym, data_names=data_names, label_names=label_names,
                      logger=self.logger, context=self._context,
                      fixed_param_names=self._fixed_param_names)

    # -- introspection -------------------------------------------------
    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        return self._gen_module(self._default_bucket_key).data_names

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        return self._gen_module(self._default_bucket_key).output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._curr_module.output_shapes

    @property
    def symbol(self):
        assert self.binded
        return self._curr_module.symbol

    def get_params(self):
        assert self.binded and self.params_initialized
        return self._curr_module.get_params()

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        self._curr_module.init_params(
            initializer=initializer, arg_params=arg_params,
            aux_params=aux_params, allow_missing=allow_missing,
            force_init=force_init, allow_extra=allow_extra)
        self.params_initialized = True

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        assert shared_module is None, \
            "shared_module for BucketingModule is not supported"
        self._grad_req = grad_req
        self._for_training = for_training
        module = self._gen_module(self._default_bucket_key)
        module.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, force_rebind=False,
                    shared_module=None, grad_req=grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        if self._monitor is not None:
            # a monitor installed before bind() follows the default bucket
            module.install_monitor(self._monitor, self._monitor_all)
        self._buckets[self._default_bucket_key] = module
        self.binded = True
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Switch to (creating if needed) the bucket's module
        (ref: bucketing_module.py — switch_bucket)."""
        assert self.binded, "call bind before switching bucket"
        if bucket_key not in self._buckets:
            module = self._gen_module(bucket_key)
            module.bind(data_shapes, label_shapes, self._for_training,
                        self.inputs_need_grad, force_rebind=False,
                        shared_module=self._buckets[
                            self._default_bucket_key],
                        grad_req=self._grad_req)
            # storage is shared with the default bucket — no param copy
            module.params_initialized = self.params_initialized
            if self._curr_module.optimizer_initialized:
                module._optimizer = self._curr_module._optimizer
                module._updater = self._curr_module._updater
                module.optimizer_initialized = True
            if self._monitor is not None:
                # monitors must follow buckets created after
                # install_monitor (ref: switch_bucket installs
                # self._monitor on fresh modules)
                module.install_monitor(self._monitor, self._monitor_all)
            self._buckets[bucket_key] = module
        else:
            module = self._buckets[bucket_key]
            module.params_initialized = self.params_initialized
        self._curr_module = module
        self._curr_bucket_key = bucket_key

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        self._curr_module.init_optimizer(kvstore, optimizer,
                                         optimizer_params,
                                         force_init=force_init)
        for mod in self._buckets.values():
            if mod is not self._curr_module:
                mod._optimizer = self._curr_module._optimizer
                mod._updater = self._curr_module._updater
                mod.optimizer_initialized = True
        self.optimizer_initialized = True

    def prepare(self, data_batch, sparse_row_id_fn=None):
        del sparse_row_id_fn
        self.switch_bucket(data_batch.bucket_key, data_batch.provide_data,
                           data_batch.provide_label)

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self.switch_bucket(data_batch.bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels, pre_sliced)

    def install_monitor(self, monitor, monitor_all=False):
        """May be called before or after bind(); the monitor follows every
        bucket, including ones created later by switch_bucket."""
        self._monitor = monitor
        self._monitor_all = monitor_all
        for mod in self._buckets.values():
            mod.install_monitor(monitor, monitor_all=monitor_all)
