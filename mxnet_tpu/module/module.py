"""Module — symbolic training over a bound executor
(ref: python/mxnet/module/module.py).

The reference slices each batch over a context list of GPUs
(DataParallelExecutorGroup) and allreduces through KVStore. Here one
executor = one jitted XLA program on the default device; data parallelism
over TPU meshes is the parallel package's job (parallel.ShardedTrainStep —
GSPMD shards the same program over the mesh, which is strictly more general
than per-GPU executor groups).
"""
from __future__ import annotations

import logging

import numpy as np

from ..base import MXNetError
from .. import initializer as init_mod
from .. import optimizer as opt_mod
from ..io.io import DataDesc
from ..ndarray.ndarray import NDArray
from ..ndarray import ndarray as _nd
from .base_module import BaseModule

__all__ = ["Module"]


def _norm_shapes(shapes):
    if shapes is None:
        return []
    out = []
    for s in shapes:
        if isinstance(s, DataDesc):
            out.append(s)
        else:
            name, shape = s[0], s[1]
            out.append(DataDesc(name, tuple(shape)))
    return out


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger=logger)
        del work_load_list, state_names
        self._symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._context = context
        self._fixed_param_names = list(fixed_param_names or [])

        arg_names = symbol.list_arguments()
        input_names = set(self._data_names) | set(self._label_names)
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()
        for n in self._data_names:
            if n not in arg_names:
                raise MXNetError(
                    "data name %r is not an argument of the symbol" % n)

        self._exec = None
        self._arg_params = None
        self._aux_params = None
        self._optimizer = None
        self._updater = None
        self._data_shapes = None
        self._label_shapes = None
        self._monitor = None
        self._fused_update = None  # None = undecided, False = ineligible

    # -- introspection -------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return [(n, o.shape) for n, o in zip(self.output_names,
                                             self._exec.outputs)] \
            if self._exec.outputs else None

    # -- binding -------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self._data_shapes = _norm_shapes(data_shapes)
        self._label_shapes = _norm_shapes(label_shapes)
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

        shape_kwargs = {d.name: d.shape for d in self._data_shapes}
        shape_kwargs.update({d.name: d.shape for d in self._label_shapes})
        req = {}
        for n in self._symbol.list_arguments():
            if n in self._param_names and n not in self._fixed_param_names \
                    and for_training:
                req[n] = grad_req if isinstance(grad_req, str) else \
                    grad_req.get(n, "write")
            elif inputs_need_grad and n in self._data_names:
                req[n] = "write"
            else:
                req[n] = "null"
        if shared_module is not None:
            # share parameter/grad/aux STORAGE with the other module: both
            # executors hold the same NDArray handles, so an update through
            # either bucket is visible to all (ref: module.py —
            # shared_module → shared_exec_group storage)
            import jax.numpy as jnp

            from ..symbol.executor import Executor

            sh = shared_module._exec
            arg_shapes, _, aux_shapes = self._symbol.infer_shape(
                **shape_kwargs)
            arg_names = self._symbol.list_arguments()
            args, args_grad = {}, {}
            for n, s in zip(arg_names, arg_shapes):
                if n in sh.arg_dict and n in self._param_names:
                    args[n] = sh.arg_dict[n]
                    if req.get(n, "null") != "null" and n in sh.grad_dict:
                        args_grad[n] = sh.grad_dict[n]
                else:
                    args[n] = NDArray(jnp.zeros(s, dtype="float32"))
                if n not in args_grad and req.get(n, "null") != "null":
                    args_grad[n] = NDArray(
                        jnp.zeros_like(args[n].data))
            aux = {n: sh.aux_dict[n] if n in sh.aux_dict
                   else NDArray(jnp.zeros(s, dtype="float32"))
                   for n, s in zip(self._symbol.list_auxiliary_states(),
                                   aux_shapes)}
            self._exec = Executor(self._symbol, self._context, args,
                                  args_grad, req, aux)
        else:
            self._exec = self._symbol.simple_bind(
                self._context, grad_req=req, **shape_kwargs)
        self.binded = True
        if self._arg_params is not None:
            self._exec.copy_params_from(self._arg_params, self._aux_params,
                                        allow_extra_params=True)
            self.params_initialized = True
            self._arg_params = None
            self._aux_params = None

    # -- params --------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"
        if initializer is None and not self.params_initialized:
            initializer = init_mod.Uniform(0.01)

        for name in self._param_names + self._aux_names:
            target = self._exec.arg_dict.get(name)
            if target is None:
                target = self._exec.aux_dict.get(name)
            src = None
            if arg_params is not None and name in arg_params:
                src = arg_params[name]
            elif aux_params is not None and name in aux_params:
                src = aux_params[name]
            if src is not None:
                target._set_data(src.data.astype(target.dtype)
                                 if isinstance(src, NDArray)
                                 else np.asarray(src, target.dtype))
            elif self.params_initialized and not force_init:
                continue
            elif initializer is not None:
                initializer(name, target)
            elif not allow_missing:
                raise MXNetError("parameter %r missing and no initializer"
                                 % name)
        self.params_initialized = True
        self._arg_params = None
        self._aux_params = None

    def get_params(self):
        assert self.binded and self.params_initialized
        arg = {n: self._exec.arg_dict[n].copy()
               for n in self._param_names}
        aux = {n: self._exec.aux_dict[n].copy() for n in self._aux_names}
        return arg, aux

    # -- optimizer -----------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        del kvstore  # facade: single-program execution needs no kvstore
        if isinstance(optimizer, opt_mod.Optimizer):
            self._optimizer = optimizer
        else:
            opt_params = dict(optimizer_params)
            # ref: module.py — init_optimizer defaults rescale_grad to
            # 1/batch_size (the executor's gradients are batch-SUMMED;
            # without this the effective lr scales with batch size).
            # Batch size comes from the DataDesc layout's batch axis —
            # a TNC-layout RNN input has it at axis 1, not 0.
            if "rescale_grad" not in opt_params and self._data_shapes:
                from ..io.io import DataDesc

                desc = self._data_shapes[0]
                axis = DataDesc.get_batch_axis(
                    getattr(desc, "layout", None))
                if 0 <= axis < len(desc.shape) and desc.shape[axis]:
                    opt_params["rescale_grad"] = 1.0 / desc.shape[axis]
            idx2name = {i: n for i, n in enumerate(self._param_names)}
            self._optimizer = opt_mod.create(
                optimizer, param_idx2name=idx2name, **opt_params)
        self._updater = opt_mod.get_updater(self._optimizer)
        self._fused_update = None  # rebuild against the new optimizer
        self.optimizer_initialized = True

    # -- execution -----------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        feed = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feed[name] = arr
        if self._label_names and data_batch.label is not None:
            for name, arr in zip(self._label_names, data_batch.label):
                if name in self._exec.arg_dict:
                    feed[name] = arr
        self._exec.forward(is_train=is_train, **feed)
        if self._monitor is not None:
            # legacy hook protocol; mx.monitor.Monitor taps via the
            # executor's monitor callback instead
            hook = getattr(self._monitor, "forward_hook", None)
            if hook is not None:
                hook(self)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    def update(self):
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        from .. import resilience
        if resilience.skip_nonfinite_enabled():
            grads = [g for g in (self._exec.grad_dict.get(n)
                                 for n in self._param_names)
                     if g is not None]
            if grads and not resilience.all_finite(grads):
                # skip-step guard (MXT_SKIP_NONFINITE): weights, optimizer
                # state, and update counts all stay untouched
                resilience.record_skipped_step()
                return
        if self._fused_update is None:
            self._fused_update = self._build_fused_update()
        if self._fused_update:
            weights = [self._exec.arg_dict[n]
                       for n in self._fused_update._names]
            grads = [self._exec.grad_dict[n]
                     for n in self._fused_update._names]
            if self._fused_update(self._updater, weights, grads):
                return  # one donated launch covered every parameter
        for i, name in enumerate(self._param_names):
            grad = self._exec.grad_dict.get(name)
            if grad is None:
                continue
            self._updater(i, grad, self._exec.arg_dict[name])

    def _build_fused_update(self):
        """Fuse the per-parameter updater loop into one donated launch —
        the same machinery (and numerics) as the gluon fused trainer/
        CachedTrainStep (gluon/train_step.py — FusedApply). Returns False
        when ineligible (unsupported optimizer, no grads); the eager loop
        then runs exactly as before."""
        from ..gluon.train_step import FusedApply

        # the updater's optimizer is what the eager loop applies (a state
        # load may have swapped it in) — fuse against that same object
        optimizer = self._updater.optimizer
        if not FusedApply.supported(optimizer):
            return False
        pairs = [(i, name) for i, name in enumerate(self._param_names)
                 if self._exec.grad_dict.get(name) is not None]
        if not pairs:
            return False
        fused = FusedApply(optimizer, [i for i, _ in pairs])
        fused._names = [name for _, name in pairs]
        return fused

    def get_outputs(self, merge_multi_context=True):
        assert self.binded
        return list(self._exec.outputs)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.inputs_need_grad
        return [self._exec.grad_dict[n] for n in self._data_names]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update_dict(
            dict(zip(self._label_names, labels or [])),
            dict(zip(self.output_names, self.get_outputs())))

    def install_monitor(self, monitor, monitor_all=False):
        """Attach a mx.monitor.Monitor to the bound executor
        (ref: BaseModule.install_monitor)."""
        assert self.binded, "call bind before install_monitor"
        self._monitor = monitor
        monitor.install(self._exec, monitor_all=monitor_all)

    # -- checkpointing (ref: module.py — save_checkpoint / load) -------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        from ..model import save_checkpoint

        arg, aux = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg, aux)
        if save_optimizer_states:
            self.save_optimizer_states("%s-%04d.states" % (prefix, epoch))

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from ..model import load_checkpoint

        sym, arg, aux = load_checkpoint(prefix, epoch)
        mod = Module(sym, **kwargs)
        mod._arg_params = arg
        mod._aux_params = aux
        if load_optimizer_states:
            mod._preloaded_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        with open(fname, "wb") as f:
            f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())
        # the fused update closed over the pre-load optimizer object
        # (hyper-params, update counts) — rebuild on next update()
        self._fused_update = None

    # set_params comes from BaseModule; params land when bound
    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        if not self.binded:
            self._arg_params = arg_params
            self._aux_params = aux_params
            self.params_initialized = True
            return
        super().set_params(arg_params, aux_params,
                           allow_missing=allow_missing,
                           force_init=force_init, allow_extra=allow_extra)
