"""Attribute scoping for symbol construction (ref: python/mxnet/
attribute.py — AttrScope). Attributes set here land on every symbol
created inside the scope — the reference's `group2ctx` model-parallel
placement rides this (`with mx.AttrScope(ctx_group='dev1')`); in this
framework placement is sharding, but the attrs still flow into the
graph for tooling/serialization parity."""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current"]


class AttrScope:
    """with-scope attaching string attributes to created symbols
    (ref: attribute.py — AttrScope)."""

    _state = threading.local()

    def __init__(self, **kwargs):
        for value in kwargs.values():
            if not isinstance(value, str):
                raise ValueError(
                    "AttrScope values must be strings, got %r" % (value,))
        self._attr = kwargs
        self._old_scope = None

    def get(self, attr=None):
        """Merge scope attrs under explicit ``attr`` (explicit wins)."""
        if not self._attr:
            return dict(attr) if attr else {}
        ret = self._attr.copy()
        if attr:
            ret.update(attr)
        return ret

    def __enter__(self):
        if not hasattr(AttrScope._state, "current"):
            AttrScope._state.current = AttrScope()
        self._old_scope = AttrScope._state.current
        attr = AttrScope._state.current._attr.copy()
        attr.update(self._attr)
        self._attr = attr
        AttrScope._state.current = self
        return self

    def __exit__(self, ptype, value, trace):
        assert self._old_scope is not None
        AttrScope._state.current = self._old_scope


def current():
    """The innermost active scope (a fresh empty one per thread)."""
    if not hasattr(AttrScope._state, "current"):
        AttrScope._state.current = AttrScope()
    return AttrScope._state.current
