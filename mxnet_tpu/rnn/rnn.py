"""RNN checkpoint helpers (ref: python/mxnet/rnn/rnn.py): fused cells
store one packed parameter vector; these save/load in the UNPACKED
per-gate format so checkpoints are interchangeable between fused and
unfused cells."""
from __future__ import annotations

from ..model import load_checkpoint, save_checkpoint

__all__ = ["save_rnn_checkpoint", "load_rnn_checkpoint",
           "do_rnn_checkpoint"]


def _as_cell_list(cells):
    return cells if isinstance(cells, (list, tuple)) else [cells]


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params,
                        aux_params):
    """save_checkpoint with cell weights unpacked
    (ref: rnn.py — save_rnn_checkpoint)."""
    args = dict(arg_params)
    for cell in _as_cell_list(cells):
        args = cell.unpack_weights(args)
    save_checkpoint(prefix, epoch, symbol, args, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """load_checkpoint + re-pack for the given cells
    (ref: rnn.py — load_rnn_checkpoint)."""
    sym, arg, aux = load_checkpoint(prefix, epoch)
    for cell in _as_cell_list(cells):
        arg = cell.pack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback that saves unpacked checkpoints
    (ref: rnn.py — do_rnn_checkpoint)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)

    return _callback
