"""Bucketing data iterator for variable-length sequences
(ref: python/mxnet/rnn/io.py — BucketSentenceIter)."""
from __future__ import annotations

import random

import numpy as np

from ..io.io import DataBatch, DataDesc, DataIter

__all__ = ["BucketSentenceIter"]


class BucketSentenceIter(DataIter):
    """Pads encoded sentences into length buckets and yields fixed-shape
    batches with a ``bucket_key`` for BucketingModule
    (ref: io.py — BucketSentenceIter). Buckets ARE the TPU story here:
    each bucket is one static shape, so XLA compiles once per bucket
    instead of once per sentence length."""

    def __init__(self, sentences, batch_size, buckets=None,
                 invalid_label=-1, data_name="data",
                 label_name="softmax_label", dtype="float32", layout="NT"):
        super().__init__(batch_size=batch_size)
        if not buckets:
            counts = np.bincount(
                [len(s) for s in sentences if len(s) > 0])
            buckets = [i for i, n in enumerate(counts)
                       if n >= batch_size]
            if not buckets:
                buckets = [max(len(s) for s in sentences)]
        buckets = sorted(buckets)

        ndiscard = 0
        self.data = [[] for _ in buckets]
        for sentence in sentences:
            if len(sentence) == 0:
                continue
            buck = np.searchsorted(buckets, len(sentence))
            if buck == len(buckets):
                ndiscard += 1
                continue
            buff = np.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[:len(sentence)] = sentence
            self.data[buck].append(buff)
        self.data = [np.asarray(x, dtype=dtype) for x in self.data]
        if ndiscard:
            import logging

            logging.warning(
                "discarded %d sentences longer than the largest bucket",
                ndiscard)

        self.batch_size = batch_size
        self.buckets = buckets
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.invalid_label = invalid_label
        self.layout = layout
        self.major_axis = layout.find("N")
        self.default_bucket_key = max(buckets)

        shape = ((batch_size, self.default_bucket_key)
                 if self.major_axis == 0
                 else (self.default_bucket_key, batch_size))
        self.provide_data = [DataDesc(data_name, shape, layout=layout)]
        self.provide_label = [DataDesc(label_name, shape, layout=layout)]

        self.idx = []
        for i, buck in enumerate(self.data):
            self.idx.extend((i, j) for j in
                            range(0, len(buck) - batch_size + 1,
                                  batch_size))
        self.curr_idx = 0
        self.nddata = []
        self.ndlabel = []
        self.reset()

    def reset(self):
        from .. import ndarray as nd

        self.curr_idx = 0
        random.shuffle(self.idx)
        for buck in self.data:
            np.random.shuffle(buck)
        self.nddata = []
        self.ndlabel = []
        for buck in self.data:
            if len(buck) == 0:
                self.nddata.append(None)
                self.ndlabel.append(None)
                continue
            # next-token labels: shift left, pad with invalid_label
            label = np.empty_like(buck)
            label[:, :-1] = buck[:, 1:]
            label[:, -1] = self.invalid_label
            self.nddata.append(nd.array(buck, dtype=self.dtype))
            self.ndlabel.append(nd.array(label, dtype=self.dtype))

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1

        if self.major_axis == 1:
            data = self.nddata[i][j:j + self.batch_size].T
            label = self.ndlabel[i][j:j + self.batch_size].T
        else:
            data = self.nddata[i][j:j + self.batch_size]
            label = self.ndlabel[i][j:j + self.batch_size]

        return DataBatch(
            [data], [label], pad=0, bucket_key=self.buckets[i],
            provide_data=[DataDesc(self.data_name, data.shape,
                                   layout=self.layout)],
            provide_label=[DataDesc(self.label_name, label.shape,
                                    layout=self.layout)])
