"""mx.rnn — legacy symbolic RNN API (ref: python/mxnet/rnn/__init__.py)."""
from .io import BucketSentenceIter
from .rnn import (do_rnn_checkpoint, load_rnn_checkpoint,
                  save_rnn_checkpoint)
from .rnn_cell import (BaseRNNCell, BidirectionalRNNCell, DropoutCell,
                       FusedRNNCell, GRUCell, LSTMCell, ModifierCell,
                       ResidualCell, RNNCell, RNNParams,
                       SequentialRNNCell, ZoneoutCell)

__all__ = ["BucketSentenceIter", "do_rnn_checkpoint",
           "load_rnn_checkpoint", "save_rnn_checkpoint", "BaseRNNCell",
           "BidirectionalRNNCell", "DropoutCell", "FusedRNNCell",
           "GRUCell", "LSTMCell", "ModifierCell", "ResidualCell",
           "RNNCell", "RNNParams", "SequentialRNNCell", "ZoneoutCell"]
