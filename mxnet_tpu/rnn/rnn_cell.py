"""Legacy symbolic RNN cells (ref: python/mxnet/rnn/rnn_cell.py) — the
pre-Gluon API used with Module/BucketingModule. Cells compose Symbol
graphs with the reference's parameter naming ("%si2h_weight" etc.) so
checkpoints and bucketing flows port over.

Unroll here is plain Python composition — the whole unrolled sequence
lowers into ONE XLA program at bind time, which is exactly the fast
shape for this backend (PERF.md: residual per-step launches cost ~3.4 ms
each on the tunnel; a fused program pays it once)."""
from __future__ import annotations

from .. import initializer as init
from .. import symbol
from ..base import MXNetError

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalRNNCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell", "ResidualCell"]


class RNNParams:
    """Container for cell parameter Variables, created on first use
    (ref: rnn_cell.py — RNNParams)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.var(name, **kwargs)
        return self._params[name]


def _normalize_sequence(length, inputs, layout, merge, in_layout=None):
    """Single merged Symbol <-> per-step list (ref: rnn_cell.py —
    _normalize_sequence). Returns (inputs, axis)."""
    assert inputs is not None, "unroll(inputs=None) is not supported"
    axis = (in_layout or layout).find("T")
    if isinstance(inputs, symbol.Symbol):
        if merge is False:
            if len(inputs.list_outputs()) != 1:
                raise MXNetError(
                    "unroll expects a single-output merged symbol")
            inputs = list(symbol.SliceChannel(
                inputs, axis=axis, num_outputs=length, squeeze_axis=1))
    else:
        if merge is True:
            inputs = [symbol.expand_dims(i, axis=axis) for i in inputs]
            inputs = symbol.Concat(*inputs, dim=axis)
    return inputs, axis


def _zeros_like_state(ref_sym, hidden, name):
    """(B, hidden) zeros derived from a (B, I) step symbol — shape-free,
    so bucketing graphs need no static batch size."""
    z1 = symbol.zeros_like(
        symbol.slice_axis(ref_sym, axis=1, begin=0, end=1))
    return symbol.tile(z1, reps=(1, hidden), name=name)


class BaseRNNCell:
    """Abstract symbolic cell (ref: rnn_cell.py — BaseRNNCell)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError

    @property
    def state_shape(self):
        return [info["shape"] for info in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=None, batch_size=0, **kwargs):
        """Initial-state symbols. With the default func, ``batch_size``
        must be given (concrete zeros); unroll's internal default uses a
        shape-free zeros-from-inputs construction instead."""
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called"
        states = []
        for info in self.state_info:
            self._init_counter += 1
            name = "%sbegin_state_%d" % (self._prefix, self._init_counter)
            # substitute batch_size at the layout's N axis (fused cells
            # carry (L*D, B, H) LNC states, not (B, H))
            shape = list(info["shape"])
            n_axis = info.get("__layout__", "NC").find("N")
            shape[n_axis] = batch_size
            shape = tuple(shape)
            if func is None:
                if batch_size <= 0:
                    raise MXNetError(
                        "begin_state() needs batch_size>0 for concrete "
                        "zeros; pass begin_state=None to unroll for the "
                        "shape-free default")
                states.append(symbol.zeros(shape=shape, name=name))
            else:
                states.append(func(name=name, shape=shape, **kwargs))
        return states

    def _default_begin_state(self, first_step):
        return [_zeros_like_state(
            first_step, info["shape"][-1],
            "%sbegin_state_%d" % (self._prefix, i))
            for i, info in enumerate(self.state_info)]

    # -- checkpoint interop (ref: rnn_cell.py unpack/pack) -------------
    def unpack_weights(self, args):
        """Fused/packed -> per-gate arg dict; plain cells pass through
        (ref: rnn_cell.py — BaseRNNCell.unpack_weights)."""
        return dict(args)

    def pack_weights(self, args):
        return dict(args)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unrolls the cell over ``length`` steps
        (ref: rnn_cell.py — BaseRNNCell.unroll)."""
        self.reset()
        inputs, _ = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self._default_begin_state(inputs[0])
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, states

    def _get_activation(self, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return symbol.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


class RNNCell(BaseRNNCell):
    """Elman cell (ref: rnn_cell.py — RNNCell)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden,
                                    name="%sh2h" % name)
        output = self._get_activation(i2h + h2h, self._activation,
                                      name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell, gate order [i,f,c,o]; forget_bias goes into the
    i2h_bias initializer like the reference (ref: rnn_cell.py —
    LSTMCell)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        self._iB = self.params.get(
            "i2h_bias", init=init.LSTMBias(forget_bias=forget_bias))
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%sh2h" % name)
        gates = i2h + h2h
        slice_gates = symbol.SliceChannel(gates, num_outputs=4,
                                          name="%sslice" % name)
        in_gate = symbol.Activation(slice_gates[0], act_type="sigmoid",
                                    name="%si" % name)
        forget_gate = symbol.Activation(slice_gates[1], act_type="sigmoid",
                                        name="%sf" % name)
        in_transform = symbol.Activation(slice_gates[2], act_type="tanh",
                                         name="%sc" % name)
        out_gate = symbol.Activation(slice_gates[3], act_type="sigmoid",
                                     name="%so" % name)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * symbol.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell, gate order [r,z,n] (ref: rnn_cell.py — GRUCell)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        prev_h = states[0]
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=prev_h, weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%sh2h" % name)
        i2h_r, i2h_z, i2h = symbol.SliceChannel(
            i2h, num_outputs=3, name="%si2h_slice" % name)
        h2h_r, h2h_z, h2h = symbol.SliceChannel(
            h2h, num_outputs=3, name="%sh2h_slice" % name)
        reset_gate = symbol.Activation(i2h_r + h2h_r, act_type="sigmoid",
                                       name="%sr_act" % name)
        update_gate = symbol.Activation(i2h_z + h2h_z, act_type="sigmoid",
                                        name="%sz_act" % name)
        next_h_tmp = symbol.Activation(i2h + reset_gate * h2h,
                                       act_type="tanh",
                                       name="%sh_act" % name)
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Whole-sequence fused cell over the RNN op (ref: rnn_cell.py —
    FusedRNNCell; cuDNN there, one fused XLA program here — same packed
    parameter layout as ops/rnn.py)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._forget_bias = forget_bias
        self._parameter = self.params.get("parameters",
                                          init=init.Xavier(factor_type="in"))
        self._directions = 2 if bidirectional else 1

    @property
    def state_info(self):
        b = self._directions * self._num_layers
        n = 2 if self._mode == "lstm" else 1
        return [{"shape": (b, 0, self._num_hidden), "__layout__": "LNC"}
                for _ in range(n)]

    @property
    def _gate_names(self):
        return {"rnn_relu": ("",), "rnn_tanh": ("",),
                "lstm": ("_i", "_f", "_c", "_o"),
                "gru": ("_r", "_z", "_o")}[self._mode]

    @property
    def _num_gates(self):
        return len(self._gate_names)

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "FusedRNNCell cannot be stepped — call unroll()")

    def _slice_weights(self, arr, li, lh):
        """Views over the packed vector in the ops/rnn.py layout (all
        weights, then all biases), named for the unfused cells
        ("%sl0_i2h_weight" = the full gate-stacked matrix)."""
        args = {}
        h, d, L = self._num_hidden, self._directions, self._num_layers
        g = self._num_gates
        p = 0
        for layer in range(L):
            in_sz = li if layer == 0 else lh * d
            for di in range(d):
                dname = ("l", "r")[di]
                args["%s%s%d_i2h_weight" % (self._prefix, dname, layer)] \
                    = arr[p:p + g * h * in_sz].reshape((g * h, in_sz))
                p += g * h * in_sz
                args["%s%s%d_h2h_weight" % (self._prefix, dname, layer)] \
                    = arr[p:p + g * h * h].reshape((g * h, h))
                p += g * h * h
        for layer in range(L):
            for di in range(d):
                dname = ("l", "r")[di]
                args["%s%s%d_i2h_bias" % (self._prefix, dname, layer)] \
                    = arr[p:p + g * h]
                p += g * h
                args["%s%s%d_h2h_bias" % (self._prefix, dname, layer)] \
                    = arr[p:p + g * h]
                p += g * h
        assert p == arr.shape[0], (p, arr.shape)
        return args

    def unpack_weights(self, args):
        from .. import ndarray as nd

        args = dict(args)
        pname = self._prefix + "parameters"
        if pname not in args:
            return args
        arr = args.pop(pname)
        h, d = self._num_hidden, self._directions
        g = self._num_gates
        total = arr.shape[0]
        # solve layer-0 input size from the packed length:
        # total = d*g*h*li + d*g*h*h + (L-1)*d*g*h*(h*d + h) + L*d*2*g*h
        deeper = sum(g * h * (h * d) + g * h * h
                     for _ in range(self._num_layers - 1)) * d
        biases = 2 * g * h * d * self._num_layers
        li = (total - biases - deeper - d * g * h * h) // (d * g * h)
        for name, view in self._slice_weights(arr, li, h).items():
            args[name] = view.copy() if hasattr(view, "copy") \
                else nd.array(view)
        return args

    def pack_weights(self, args):
        from .. import ndarray as nd
        import numpy as np

        args = dict(args)
        d, L = self._directions, self._num_layers
        chunks = []
        for layer in range(L):
            for di in range(d):
                dname = ("l", "r")[di]
                for kind in ("i2h", "h2h"):
                    chunks.append(args.pop(
                        "%s%s%d_%s_weight" % (
                            self._prefix, dname, layer, kind)))
        for layer in range(L):
            for di in range(d):
                dname = ("l", "r")[di]
                for kind in ("i2h", "h2h"):
                    chunks.append(args.pop(
                        "%s%s%d_%s_bias" % (
                            self._prefix, dname, layer, kind)))
        flat = np.concatenate(
            [c.asnumpy().reshape(-1) if hasattr(c, "asnumpy")
             else np.asarray(c).reshape(-1) for c in chunks])
        args[self._prefix + "parameters"] = nd.array(flat)
        return args

    def _fused_begin_state(self, data_tnc):
        # (L*D, B, H) zeros from the (T, B, I) data symbol, shape-free
        z = symbol.zeros_like(symbol.slice_axis(
            symbol.slice_axis(data_tnc, axis=0, begin=0, end=1),
            axis=2, begin=0, end=1))  # (1, B, 1)
        state = symbol.tile(
            z, reps=(self._directions * self._num_layers, 1,
                     self._num_hidden))
        n = 2 if self._mode == "lstm" else 1
        return [state] * n

    def _default_begin_state(self, first_step):
        # nested (Sequential/Bidirectional) composition hands a (B, I)
        # step symbol; lift it to the (L*D, B, H) LNC state the RNN op
        # needs
        z = symbol.expand_dims(symbol.zeros_like(symbol.slice_axis(
            first_step, axis=1, begin=0, end=1)), axis=0)  # (1, B, 1)
        state = symbol.tile(
            z, reps=(self._directions * self._num_layers, 1,
                     self._num_hidden))
        n = 2 if self._mode == "lstm" else 1
        return [state] * n

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis = _normalize_sequence(length, inputs, layout, True)
        if axis == 1:  # NTC -> the op's TNC
            inputs = symbol.swapaxes(inputs, dim1=0, dim2=1)
        if begin_state is None:
            begin_state = self._fused_begin_state(inputs)
        states = begin_state
        kwargs = {}
        if self._mode == "lstm":
            kwargs["state_cell"] = states[1]
        rnn = symbol.RNN(inputs, self._parameter, states[0],
                         state_size=self._num_hidden,
                         num_layers=self._num_layers,
                         bidirectional=self._bidirectional,
                         p=self._dropout, state_outputs=True,
                         mode=self._mode, name=self._prefix + "rnn",
                         **kwargs)
        outputs = rnn[0]
        if self._get_next_state:
            states = [rnn[1], rnn[2]] if self._mode == "lstm" else [rnn[1]]
        else:
            states = []
        if axis == 1:
            outputs = symbol.swapaxes(outputs, dim1=0, dim2=1)
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, states

    def unfuse(self):
        """Equivalent SequentialRNNCell of unfused cells
        (ref: rnn_cell.py — FusedRNNCell.unfuse)."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden,
                                          activation="relu", prefix=p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden,
                                          activation="tanh", prefix=p),
            "lstm": lambda p: LSTMCell(self._num_hidden, prefix=p,
                                       forget_bias=self._forget_bias),
            "gru": lambda p: GRUCell(self._num_hidden, prefix=p),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalRNNCell(
                    get_cell("%sl%d_" % (self._prefix, i)),
                    get_cell("%sr%d_" % (self._prefix, i)),
                    output_prefix="%sbi_l%d_" % (self._prefix, i)))
            else:
                stack.add(get_cell("%sl%d_" % (self._prefix, i)))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(
                    self._dropout, prefix="%s_dropout%d_" % (
                        self._prefix, i)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Sequentially stacked cells (ref: rnn_cell.py)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            assert cell._own_params, \
                "Either specify params for SequentialRNNCell or child " \
                "cells, not both."
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def _default_begin_state(self, first_step):
        return sum([c._default_begin_state(first_step)
                    for c in self._cells], [])

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            assert not isinstance(cell, BidirectionalRNNCell), \
                "BidirectionalRNNCell must only be used with unroll"
            n = len(cell.state_info)
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        num_cells = len(self._cells)
        if begin_state is None:
            # normalize once; the per-step list feeds both the state
            # probe and the first child's unroll (no duplicate slicing)
            inputs, _ = _normalize_sequence(length, inputs, layout, False)
            begin_state = self._default_begin_state(inputs[0])
        p = 0
        next_states = []
        for i, cell in enumerate(self._cells):
            n = len(cell.state_info)
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs)
            next_states.extend(states)
        return inputs, next_states


class BidirectionalRNNCell(BaseRNNCell):
    """Runs two cells over the sequence in opposite directions
    (ref: rnn_cell.py — BidirectionalRNNCell)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__(prefix="", params=params)
        self._output_prefix = output_prefix
        self._cells = [l_cell, r_cell]

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def _default_begin_state(self, first_step):
        return sum([c._default_begin_state(first_step)
                    for c in self._cells], [])

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self._default_begin_state(inputs[0])
        states = begin_state
        l_cell, r_cell = self._cells
        n_l = len(l_cell.state_info)
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=states[:n_l], layout=layout,
            merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[n_l:], layout=layout, merge_outputs=False)
        outputs = [symbol.Concat(l_o, r_o, dim=1,
                                 name="%st%d" % (self._output_prefix, i))
                   for i, (l_o, r_o) in enumerate(
                       zip(l_outputs, reversed(r_outputs)))]
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, l_states + r_states


class DropoutCell(BaseRNNCell):
    """Dropout on inputs (ref: rnn_cell.py — DropoutCell). train_mode is
    resolved at bind time by the executor's is_train flag."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        assert isinstance(dropout, (int, float))
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def _default_begin_state(self, first_step):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(data=inputs, p=self.dropout)
        return inputs, states


class ModifierCell(BaseRNNCell):
    """Base for cells wrapping another cell (ref: rnn_cell.py)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(**kwargs)
        self.base_cell._modified = True
        return begin

    def _default_begin_state(self, first_step):
        return self.base_cell._default_begin_state(first_step)

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)


class ZoneoutCell(ModifierCell):
    """Zoneout (ref: rnn_cell.py — ZoneoutCell; Krueger et al.
    1606.01305)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, FusedRNNCell), \
            "FusedRNNCell does not support zoneout. Please unfuse first."
        assert not isinstance(base_cell, BidirectionalRNNCell), \
            "BidirectionalRNNCell does not support zoneout. " \
            "Please add ZoneoutCell to the cells underneath instead."
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        cell = self.base_cell
        p_outputs, p_states = self.zoneout_outputs, self.zoneout_states
        next_output, next_states = cell(inputs, states)

        def mask(p, like):
            return symbol.Dropout(symbol.ones_like(like), p=p)

        prev_output = self.prev_output if self.prev_output is not None \
            else symbol.zeros_like(next_output)
        output = symbol.where(mask(p_outputs, next_output), next_output,
                              prev_output) \
            if p_outputs != 0.0 else next_output
        states = [symbol.where(mask(p_states, new_s), new_s, old_s)
                  for new_s, old_s in zip(next_states, states)] \
            if p_states != 0.0 else next_states
        self.prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    """Adds input to output (ref: rnn_cell.py — ResidualCell)."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = symbol.elemwise_add(output, inputs,
                                     name="%s_plus_residual" % output.name)
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs)
        self.base_cell._modified = True
        merge_outputs = isinstance(outputs, symbol.Symbol) \
            if merge_outputs is None else merge_outputs
        inputs, _ = _normalize_sequence(length, inputs, layout,
                                        merge_outputs)
        if merge_outputs:
            outputs = symbol.elemwise_add(outputs, inputs)
        else:
            outputs = [symbol.elemwise_add(o, i)
                       for o, i in zip(outputs, inputs)]
        return outputs, states
