"""AOT warm-start — compile the hot path before the hot path needs it.

PERF.md's worst number is not a throughput: a 4-layer GPT forward
recompiled every attention call for 63 seconds, and the r4 outage was a
crash mid-compile. With the persistent compilation cache
(compile_cache.py) those compiles survive the process; this module
makes a NEW process replay them ahead of time:

- Kernel entry points (flash fwd/bwd, BN fwd/bwd) record their shape
  signatures into the tuning table at dispatch. ``warmup()`` rebuilds
  each signature as abstract ``ShapeDtypeStruct`` args and
  AOT-lowers-and-compiles the same programs — no device math, no real
  data, every XLA compile lands now (from the persistent cache when a
  previous process already paid it).

- Fused-step entry points (CachedTrainStep, the Trainer's
  ``_FusedUpdate``) register themselves when built; their
  ``aot_warmup()`` lowers the donated step program from the live
  parameter shapes. A resumed trainer calls ``tuning.warmup()`` after
  ``load_states`` and the first real step performs zero hot-path JIT.

Everything here is CPU-runnable: tier-1 asserts the compile counters
around a warmup() call and around a warm-started second process.
"""
from __future__ import annotations

import time
import weakref

from . import compile_cache
from . import table as _table_mod

_live_steps = weakref.WeakSet()


def _telemetry():
    from .. import telemetry

    return telemetry


def register_step(step):
    """Track a live fused entry point (an object with ``aot_warmup()``)
    so a bare ``warmup()`` can compile it without the caller threading
    references around."""
    _live_steps.add(step)


def record_signature(entry_point, spec):
    """Remember one dispatched shape signature for warm-start replay."""
    return _table_mod.table().record_signature(entry_point, spec)


def signatures(entry_point=None):
    return _table_mod.table().signatures(entry_point)


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _warm_flash(spec):
    """AOT-compile the flash custom-VJP forward and backward programs
    for one recorded signature."""
    import jax

    from ..ops import attention as A

    causal = bool(spec["causal"])
    sm_scale = float(spec["sm_scale"])  # sync-ok: host float from JSON
    q = _sds(spec["q_shape"], spec["dtype"])
    k = _sds(spec["k_shape"], spec["dtype"])
    v = _sds(spec["v_shape"], spec["dtype"])
    if spec.get("bias_shape"):
        b = _sds(spec["bias_shape"], spec.get("bias_dtype", spec["dtype"]))

        def fwd(q_, k_, v_, b_):
            return A._flash_core(q_, k_, v_, b_, causal, sm_scale)

        jax.jit(fwd).lower(q, k, v, b).compile()
        jax.jit(jax.grad(lambda q_, k_, v_, b_: fwd(q_, k_, v_, b_).sum(),
                         argnums=(0, 1, 2))).lower(q, k, v, b).compile()
    else:
        def fwd(q_, k_, v_):
            return A._flash_core(q_, k_, v_, None, causal, sm_scale)

        jax.jit(fwd).lower(q, k, v).compile()
        jax.jit(jax.grad(lambda q_, k_, v_: fwd(q_, k_, v_).sum(),
                         argnums=(0, 1, 2))).lower(q, k, v).compile()
    return "flash_attention"


def _warm_bn(spec):
    """AOT-compile the BatchNorm custom-VJP core (fwd + grad) for one
    recorded signature."""
    import jax

    from ..ops import nn as _nn

    eps = float(spec["eps"])  # sync-ok: host float from JSON
    red = tuple(spec["red"])
    x = _sds(spec["x_shape"], spec["dtype"])
    g = _sds(spec["g_shape"], spec.get("g_dtype", "float32"))
    b = _sds(spec["g_shape"], spec.get("g_dtype", "float32"))

    def fwd(x_, g_, b_):
        return _nn._bn_core(eps, red, x_, g_, b_)

    jax.jit(fwd).lower(x, g, b).compile()
    jax.jit(jax.grad(lambda x_, g_, b_: fwd(x_, g_, b_)[0].sum(),
                     argnums=(0, 1, 2))).lower(x, g, b).compile()
    return "batch_norm"


def _warm_paged(spec):
    """AOT-compile the ragged paged attention decode program for one
    recorded signature (both the jitted dispatch a serving step traces
    through and the standalone op a request-path eval would hit)."""
    import jax
    import jax.numpy as jnp

    from ..ops import attention as A

    sm_scale = float(spec["sm_scale"])  # sync-ok: host float from JSON
    q = _sds(spec["q_shape"], spec["dtype"])
    pool_dtype = spec.get("pool_dtype", spec["dtype"])
    kp = _sds(spec["pool_shape"], pool_dtype)
    vp = _sds(spec["pool_shape"], pool_dtype)
    pt = _sds((spec["q_shape"][0], spec["max_pages"]), jnp.int32)
    cl = _sds((spec["q_shape"][0],), jnp.int32)
    if spec.get("quantized"):
        sc = _sds(tuple(spec["pool_shape"][:-1]), jnp.float32)

        def fwd(q_, kp_, vp_, pt_, cl_, ks_, vs_):
            return A.ragged_paged_attention(q_, kp_, vp_, pt_, cl_,
                                            sm_scale=sm_scale,
                                            k_scales=ks_, v_scales=vs_)

        jax.jit(fwd).lower(q, kp, vp, pt, cl, sc, sc).compile()
        return "paged_attention"

    def fwd(q_, kp_, vp_, pt_, cl_):
        return A.ragged_paged_attention(q_, kp_, vp_, pt_, cl_,
                                        sm_scale=sm_scale)

    jax.jit(fwd).lower(q, kp, vp, pt, cl).compile()
    return "paged_attention"


def warmup(steps=(), kernels=True, include_live=True, reason=None):
    """AOT-lower-and-compile the canonical entry points from recorded
    shape signatures.

    ``steps``: fused entry points (CachedTrainStep / _FusedUpdate /
    parallel.ShardedTrainStep — anything with ``aot_warmup()``) to
    compile in addition to every live registered one
    (``include_live=False`` restricts to ``steps``). ``kernels=False``
    skips the library-kernel (flash/BN) signatures. ``reason`` tags the
    emitted telemetry event — the elastic reshard path passes
    ``reason="reshard"`` so warm-compiles triggered by a mesh change are
    distinguishable from resume warm-starts in the JSONL stream.

    Returns a summary dict: entries warmed, compiles performed, compile
    seconds, cache hits/misses — on a warm persistent cache the same
    entries land as hits in a fraction of the time.
    """
    compile_cache.install_listeners()
    compile_cache.setup()
    t0 = time.perf_counter()
    before = compile_cache.compile_stats()
    warmed, errors = [], []
    if kernels:
        for kind, fn in (("flash_attention", _warm_flash),
                         ("batch_norm", _warm_bn),
                         ("paged_attention", _warm_paged)):
            for spec in signatures(kind):
                try:
                    warmed.append(fn(spec))
                except Exception as e:  # noqa: BLE001 — warmup is advisory
                    errors.append("%s: %r" % (kind, e))
    seen = set()
    live = list(_live_steps) if include_live else []
    for step in list(steps) + live:
        if id(step) in seen:
            continue
        seen.add(id(step))
        try:
            if step.aot_warmup() is not False:
                warmed.append(type(step).__name__)
        except Exception as e:  # noqa: BLE001
            errors.append("%s: %r" % (type(step).__name__, e))
    after = compile_cache.compile_stats()
    dt = time.perf_counter() - t0
    summary = {
        "entries": warmed,
        "errors": errors,
        "seconds": round(dt, 6),
        "compiles": after["compiles"] - before["compiles"],
        "compile_seconds": round(
            after["compile_seconds"] - before["compile_seconds"], 6),
        "cache_hits": after["cache_hits"] - before["cache_hits"],
        "cache_misses": after["cache_misses"] - before["cache_misses"],
        "cache_dir": compile_cache.cache_dir(),
    }
    if reason is not None:
        summary["reason"] = str(reason)
    tel = _telemetry()
    tel.histogram(
        "mxt_warmup_seconds",
        "Wall time of tuning.warmup() AOT warm-start passes.").observe(dt)
    tel.emit_event("warmup", **summary)
    # warm-start implies the table (incl. any new signatures) should
    # survive this process too
    try:
        _table_mod.save()
    except OSError:
        pass
    return summary
