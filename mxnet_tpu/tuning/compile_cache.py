"""Persistent XLA compilation cache + compile observability.

Two jobs:

1. **Point JAX's persistent compilation cache at
   ``MXT_COMPILE_CACHE_DIR``** (setup()) with the thresholds dropped to
   zero so every program caches — on CPU tier-1 the compiles are small,
   and on the chip the 63-second attention compiles (PERF.md) are
   exactly what must never be paid twice. A second process compiling
   the same program deserializes from disk instead of running XLA; the
   r4 outage (crash *mid-compile*) becomes a cheap replay.

2. **Count and time every compile** via ``jax.monitoring`` listeners:
   ``/jax/core/compile/*_duration`` duration events feed the
   ``mxt_compile_seconds{phase=trace|lower|compile}`` histogram and the
   ``mxt_compiles_total`` counter; ``/jax/compilation_cache/cache_hits``
   / ``cache_misses`` feed ``mxt_compile_cache_{hits,misses}_total``.
   ``compile_stats()`` snapshots all of it for bench deltas and the
   zero-JIT acceptance assert: on a warm start, the hot loop's
   cache_misses delta is 0.

Listeners are installed once at package import (mxnet_tpu/__init__
imports tuning); they are passive counters — observability must never
take the process down, so every handler swallows its own errors.
"""
from __future__ import annotations

import threading

_lock = threading.Lock()
_installed = False
_setup_dir = None

# module-level mirror of the telemetry counters: cheap consistent
# snapshots for compile_stats() deltas without walking the registry
_stats = {"compiles": 0, "compile_seconds": 0.0, "trace_seconds": 0.0,
          "cache_hits": 0, "cache_misses": 0}

_PHASES = {
    "/jax/core/compile/jaxpr_trace_duration": "trace",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "lower",
    "/jax/core/compile/backend_compile_duration": "compile",
}


def _telemetry():
    from .. import telemetry

    return telemetry


def _config():
    from .. import config

    return config


def _on_duration(name, secs, **kw):
    try:
        phase = _PHASES.get(name)
        if phase is None:
            return
        with _lock:
            if phase == "compile":
                _stats["compiles"] += 1
                _stats["compile_seconds"] += secs
            elif phase == "trace":
                _stats["trace_seconds"] += secs
        _telemetry().record_compile(phase, secs)
    except Exception:  # noqa: BLE001 — never break a compile over metrics
        pass


def _on_event(name, **kw):
    try:
        if name == "/jax/compilation_cache/cache_hits":
            with _lock:
                _stats["cache_hits"] += 1
            _telemetry().record_compile_cache(hit=True)
        elif name == "/jax/compilation_cache/cache_misses":
            with _lock:
                _stats["cache_misses"] += 1
            _telemetry().record_compile_cache(hit=False)
    except Exception:  # noqa: BLE001
        pass


def install_listeners():
    """Register the jax.monitoring listeners (idempotent)."""
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
    import jax

    jax.monitoring.register_event_duration_secs_listener(_on_duration)
    jax.monitoring.register_event_listener(_on_event)


def setup(cache_dir=None):
    """Enable the persistent compilation cache. ``cache_dir`` defaults
    to ``MXT_COMPILE_CACHE_DIR``; returns the active directory or None
    (unset = feature off, nothing touched). Idempotent per directory."""
    global _setup_dir
    if cache_dir is None:
        cache_dir = _config().get("MXT_COMPILE_CACHE_DIR")
    if not cache_dir:
        return _setup_dir
    with _lock:
        if _setup_dir == cache_dir:
            return _setup_dir
    import jax

    jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    # cache EVERYTHING: the default thresholds skip small/fast programs,
    # but tier-1 runs on CPU where every compile is small — and the
    # zero-JIT-resume contract is per program, not per expensive program
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    with _lock:
        _setup_dir = str(cache_dir)
    return _setup_dir


def cache_dir():
    """The directory setup() activated (None = persistent cache off)."""
    return _setup_dir


def compile_stats():
    """One consistent snapshot: compiles, compile_seconds,
    trace_seconds, cache_hits, cache_misses (process totals — diff two
    snapshots to scope a window)."""
    with _lock:
        return dict(_stats)
