"""Versioned on-disk tuning table — the per-shape kernel-config store.

The reference framework ships MXNET_CUDNN_AUTOTUNE_DEFAULT: the first
convolution at a new shape races every cuDNN algo and the winner is
memoized per shape for the life of the process. This module is that
memo made durable and explicit: every decision the autotuner makes —
flash-attention (block_q, block_k), BN-backward block_rows, and the
XLA-vs-Pallas backend choice — is keyed by

    (op, shape-bucket, dtype, causal, device_kind)

and stored in one JSON file (``MXT_TUNE_TABLE``), versioned so a stale
or corrupted table degrades to the heuristic cost model instead of
crashing or silently mis-tiling. The same file carries the **shape
signatures** recorded at kernel/step dispatch, which
``tuning.warmup()`` replays to AOT-compile a fresh process's hot path.

Shape bucketing bounds table growth: query/key sequence lengths round
up to the next multiple of 64 (exact below 64), BN row counts to the
next power of two. A config chosen for the bucket is tiling-legal for
every shape inside it because the kernels pad-and-mask to block
multiples — bucketing only costs (bounded, modeled) padding waste.

Lookups bump ``mxt_tune_cache_hits_total`` / ``_misses_total`` so a
serving replica's warm/cold tuning state is visible in ``mxt_top`` and
the bench rows.
"""
from __future__ import annotations

import json
import os
import threading

TABLE_VERSION = 1

_MAX_SIGNATURES = 64  # per entry point — warmup replay stays bounded


def _config():
    from .. import config

    return config


def _telemetry():
    from .. import telemetry

    return telemetry


def device_kind():
    """Tuning-key device identity: configs measured on one chip
    generation must not be served to another (or to CPU)."""
    import jax

    try:
        kind = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 — no backend: still key consistently
        kind = "unknown"
    return str(kind).replace(" ", "_").replace("|", "_")


def bucket_seq(t):
    """Sequence-length bucket: exact below 64, else next multiple of 64
    (ceil(t/64) distinct buckets — bounded growth, bounded padding)."""
    t = int(t)
    if t <= 64:
        return t
    return -(-t // 64) * 64


def bucket_rows(m):
    """BN row bucket: next power of two (rows = batch*spatial can be
    anything; pow2 keeps the table tiny)."""
    m = int(m)
    p = 1
    while p < m:
        p <<= 1
    return p


def attn_key(q_shape, kv_len, dtype, causal, kind=None):
    b, h, tq, d = q_shape
    return "flash|bh%d|q%d|k%d|d%d|%s|c%d|%s" % (
        bucket_rows(b * h), bucket_seq(tq), bucket_seq(kv_len), d,
        str(dtype), 1 if causal else 0, kind or device_kind())


def bn_key(m, c, dtype, kind=None):
    return "bn_bwd|m%d|c%d|%s|%s" % (bucket_rows(m), int(c), str(dtype),
                                     kind or device_kind())


def paged_key(q_shape, page_size, max_pages, dtype, kind=None):
    """Decode-shape bucket for the ragged paged attention kernel: batch
    slots round to the next power of two, the page-table width (context
    capacity) likewise — a serving engine growing a sequence page by
    page must not churn new table entries every page."""
    b, h, d = q_shape
    return "paged|b%d|h%d|d%d|s%d|p%d|%s|%s" % (
        bucket_rows(b), int(h), int(d), int(page_size),
        bucket_rows(max_pages), str(dtype), kind or device_kind())


def quant_key(op, k, n, dtype, kind=None):
    """Quantized-vs-float kernel bucket for one decode matmul shape:
    (reduction k, output n) both round to the next power of two — the
    same bounded-growth discipline as paged_key, keyed per device kind
    because the int8 win is a memory-bandwidth property of the chip."""
    return "quant|%s|k%d|n%d|%s|%s" % (
        str(op), bucket_rows(k), bucket_rows(n), str(dtype),
        kind or device_kind())


class TuneTable:
    """One process's view of the tuning table: entries + signatures,
    loaded from ``path`` when it exists (corrupted/stale files are
    ignored with a note — the heuristic path keeps working), saved
    atomically (tmp + fsync + replace, the checkpoint idiom)."""

    def __init__(self, path=None):
        self.path = path
        self.load_error = None
        self._lock = threading.Lock()
        self._entries = {}
        self._signatures = {}
        self._dirty = False
        if path and os.path.exists(path):
            self._load(path)

    def _load(self, path):
        try:
            with open(path) as f:
                raw = json.load(f)
            if not isinstance(raw, dict):
                raise ValueError("tune table root is not an object")
            if raw.get("version") != TABLE_VERSION:
                raise ValueError("tune table version %r != %d"
                                 % (raw.get("version"), TABLE_VERSION))
            entries = raw.get("entries", {})
            sigs = raw.get("signatures", {})
            if not isinstance(entries, dict) or not isinstance(sigs, dict):
                raise ValueError("tune table sections malformed")
            self._entries = {str(k): dict(v) for k, v in entries.items()
                             if isinstance(v, dict)}
            self._signatures = {str(k): list(v)[:_MAX_SIGNATURES]
                                for k, v in sigs.items()
                                if isinstance(v, list)}
        except (OSError, ValueError, TypeError) as e:
            # a bad table must never take training down: note it, start
            # empty, and let the heuristic cost model answer everything
            self.load_error = "%s: %s" % (type(e).__name__, e)
            self._entries = {}
            self._signatures = {}
            _telemetry().counter(
                "mxt_tune_table_load_errors_total",
                "Tune-table files ignored as corrupted/stale.").inc()

    # -- decisions --------------------------------------------------------
    def lookup(self, key):
        """The stored config dict for ``key`` (None = miss). Every call
        lands in the tune-cache hit/miss counters."""
        with self._lock:
            ent = self._entries.get(key)
        _telemetry().record_tune_lookup(hit=ent is not None)
        return dict(ent) if ent is not None else None

    def peek(self, key):
        """lookup() without touching the hit/miss counters (tests,
        introspection)."""
        with self._lock:
            ent = self._entries.get(key)
        return dict(ent) if ent is not None else None

    def record(self, key, entry):
        """Store a decision. ``source`` ('measured'/'heuristic') rides
        the entry; a measured entry is never downgraded by a heuristic
        re-record for the same key."""
        with self._lock:
            old = self._entries.get(key)
            if old is not None and old.get("source") == "measured" \
                    and entry.get("source") != "measured":
                return dict(old)
            self._entries[key] = dict(entry)
            self._dirty = True
        return dict(entry)

    def entries(self):
        with self._lock:
            return {k: dict(v) for k, v in self._entries.items()}

    # -- warmup signatures ------------------------------------------------
    def record_signature(self, entry_point, spec):
        """Remember one dispatched shape signature (dict, JSON-able) for
        ``entry_point`` — the AOT warm-start replay list. Deduplicated;
        bounded per entry point."""
        spec = dict(spec)
        with self._lock:
            sigs = self._signatures.setdefault(str(entry_point), [])
            if spec in sigs:
                return False
            if len(sigs) >= _MAX_SIGNATURES:
                return False
            sigs.append(spec)
            self._dirty = True
        return True

    def signatures(self, entry_point=None):
        with self._lock:
            if entry_point is not None:
                return [dict(s) for s in
                        self._signatures.get(str(entry_point), [])]
            return {k: [dict(s) for s in v]
                    for k, v in self._signatures.items()}

    # -- persistence ------------------------------------------------------
    @property
    def dirty(self):
        return self._dirty

    def save(self, path=None):
        """Atomically write the table. Returns the path written, or None
        when there is nowhere to write (no path configured)."""
        path = path or self.path
        if not path:
            return None
        with self._lock:
            payload = {"version": TABLE_VERSION,
                       "entries": dict(self._entries),
                       "signatures": dict(self._signatures)}
            self._dirty = False
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp.%d" % os.getpid()
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=0, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self.path = path
        return path


_table = None
_table_path = None
_table_lock = threading.Lock()


def table():
    """The process-default TuneTable, bound to the CURRENT
    ``MXT_TUNE_TABLE`` value — a path change (tests, sweeps) swaps in a
    fresh instance loaded from the new file."""
    global _table, _table_path
    path = _config().get("MXT_TUNE_TABLE")
    if _table is not None and path == _table_path:
        return _table
    with _table_lock:
        if _table is None or path != _table_path:
            if _table is not None and _table.dirty:
                try:
                    _table.save()
                except OSError:
                    pass  # old location gone: decisions were best-effort
            _table = TuneTable(path)
            _table_path = path
    return _table


def reset():
    """Drop the in-memory table (tests). The on-disk file is untouched;
    the next table() call reloads it."""
    global _table, _table_path
    with _table_lock:
        _table = None
        _table_path = None


def save():
    """Persist the default table if a path is configured."""
    return table().save()
