"""Block-size autotuner for the Pallas kernels + per-shape backend choice.

Two halves, per TVM's split (PAPERS.md arXiv 1802.04799 — search-based
config selection beats fixed heuristics), scoped to block/grid configs:

1. **Candidate generation + deterministic cost model** (always
   available, CPU/CI path). Candidates are TPU-tiling-legal by
   construction: multiples of 8 in the sublane dimension, lane-friendly
   (128-multiple preferred) in the key dimension, VMEM-budgeted. The
   cost model charges padded work (the kernels pad-and-mask partial
   blocks, so a block that divides the padded shape badly wastes real
   MXU cycles — BENCH_r02's `partial_errors` class), per-grid-step
   overhead, and tile-shape penalties. It is a pure function of the
   shape: same inputs, same config, no measurement noise in CI.

2. **Timed micro-benchmarks on device** (`measure=True`, the default
   under ``MXT_TUNE_MODE=auto`` on a real TPU): each candidate runs a
   short timed loop and the empirical winner is recorded as
   ``source="measured"`` — which the table never lets a later heuristic
   overwrite. Measurement also settles the **XLA-vs-Pallas** choice per
   shape (the per-call replacement for the global ``MXT_BN_PALLAS`` /
   reference-path switches), per the fusion-analysis motivation (arXiv
   2301.13062): small shapes often lose to XLA's fused reference.

Measurement loops block on device results by design — they are the
tuning path, not the training hot path, and every sync is marked for
tools/check_host_syncs.py.
"""
from __future__ import annotations

import math
import time

from . import table as _table_mod

_VMEM_BUDGET = 12 * 1024 * 1024  # leave headroom under ~16 MB/core
_LANE = 128
_SUBLANE = 8


def _config():
    from .. import config

    return config


def _round8(n):
    return max(_SUBLANE, -(-int(n) // _SUBLANE) * _SUBLANE)


def _pad_to(n, block):
    return -(-int(n) // int(block)) * int(block)


def _itemsize(dtype):
    import numpy as np

    try:
        return np.dtype(dtype).itemsize
    except TypeError:
        return 4


# --------------------------------------------------------------------------
# flash attention
# --------------------------------------------------------------------------
def attention_candidates(tq, tk, d, dtype):
    """Tiling-legal (block_q, block_k) candidates for a (Tq, Tk, D)
    attention shape. Shape-aware: blocks never exceed the padded
    sequence, the K/V VMEM residency fits the budget, and a non-multiple
    shape gets divisor-friendly small blocks among the candidates
    instead of only worst-case-padding large ones."""
    tq8, tk8 = _round8(tq), _round8(tk)
    qs = sorted({min(b, tq8) for b in (8, 16, 32, 64, 128, 256, 512)})
    ks = sorted({min(b, tk8) for b in (32, 64, 128, 256, 512)})
    out = []
    isz = _itemsize(dtype)
    for bq in qs:
        for bk in ks:
            pk = _pad_to(tk, bk)
            # kernel VMEM residency: q block, full padded K+V, f32 acc +
            # score tile (matches _flash_forward_pallas's spec layout)
            vmem = (bq * d + 2 * pk * d) * isz + bq * bk * 4 + bq * d * 4
            if vmem > _VMEM_BUDGET:
                continue
            out.append((bq, bk))
    if not out:  # degenerate (huge D): minimal legal tile
        out.append((_SUBLANE, _SUBLANE))
    return out


def attention_cost(tq, tk, d, bq, bk, dtype):
    """Deterministic relative cost of one (block_q, block_k) config:
    padded score-matrix work, grid-step overhead, and tile-shape
    penalties. Unitless — only the argmin matters."""
    pq, pk = _pad_to(tq, bq), _pad_to(tk, bk)
    cost = 1.0 * pq * pk  # compute incl. padding waste
    grid_q = pq // bq
    kv_steps = pk // bk
    # per-grid-step / per-kv-iteration fixed overhead (loop + DMA issue)
    cost *= 1.0 + 0.004 * grid_q + 0.001 * grid_q * kv_steps
    if bk % _LANE:
        cost *= 1.20  # lane dim off the 128 register width
    if bq < 64:
        cost *= 1.0 + (64 - bq) / 256.0  # underfilled MXU sublanes
    return cost


def heuristic_attention(q_shape, kv_len, dtype, causal):
    """Cost-model argmin config + backend choice for one shape."""
    _, _, tq, d = q_shape
    tk = kv_len
    best, best_cost = None, math.inf
    for bq, bk in attention_candidates(tq, tk, d, dtype):
        c = attention_cost(tq, tk, d, bq, bk, dtype)
        if c < best_cost:
            best, best_cost = (bq, bk), c
    # XLA-vs-Pallas per shape: tiny sequences don't amortize the kernel's
    # online-softmax bookkeeping — XLA's fused reference wins there
    backend = "pallas" if (tq >= 64 and tk >= 128) else "xla"
    return {"backend": backend, "block_q": best[0], "block_k": best[1],
            "source": "heuristic", "score": round(best_cost, 3)}


def measure_attention(q, k, v, bias, causal, sm_scale, interpret=False,
                      iters=None, candidates=None):
    """Time each candidate (and the XLA reference) on the live arrays;
    returns the winning entry dict. Runs OUTSIDE the training hot path
    (first call per shape bucket, or an explicit sweep)."""
    from ..ops import attention as A

    iters = iters or int(_config().get("MXT_TUNE_ITERS"))
    tq, d = q.shape[2], q.shape[3]
    tk = k.shape[2]
    cands = candidates or attention_candidates(tq, tk, d, q.dtype)
    timings = {}
    for bq, bk in cands:
        try:
            def run(bq=bq, bk=bk):
                out, _ = A._flash_forward_pallas(
                    q, k, v, bias, causal, sm_scale, bq, bk,
                    interpret=interpret)
                return out
            timings[("pallas", bq, bk)] = _time(run, iters)
        except Exception:  # noqa: BLE001 — candidate failed to lower: skip
            continue

    def ref():
        return A._attention_reference(q, k, v, bias, causal, sm_scale)
    timings[("xla", 0, 0)] = _time(ref, iters)

    (backend, bq, bk), score = min(timings.items(), key=lambda kv: kv[1])
    return {"backend": backend, "block_q": bq, "block_k": bk,
            "source": "measured", "score": round(score * 1e3, 6)}


# --------------------------------------------------------------------------
# ragged paged attention (decode)
# --------------------------------------------------------------------------
def paged_candidates(heads, head_dim, page_size, dtype):
    """Legal head-block widths for the paged decode kernel: divisors of
    the head count (the kernel statically unrolls per-head matvecs over
    the block), VMEM-bounded by one page of K+V per head in the block
    plus the f32 softmax state."""
    isz = _itemsize(dtype)
    out = []
    for bh in (1, 2, 4, 8, 16, 32):
        if bh > heads or heads % bh:
            continue
        vmem = (2 * page_size * bh * head_dim + bh * head_dim) * isz \
            + bh * (page_size + 2 * _LANE + head_dim) * 4
        if vmem > _VMEM_BUDGET:
            continue
        out.append(bh)
    return out or [1]


def paged_cost(heads, head_dim, page_size, max_pages, bh):
    """Deterministic relative cost of one head-block width. Decode is
    grid-overhead dominated (every grid step moves one page and does a
    handful of matvecs), so wider head blocks amortize steps — charged
    against the unrolled-code/VMEM pressure of very wide blocks."""
    steps = (heads // bh) * max_pages
    work = steps * (8.0 + 0.002 * bh * page_size * head_dim)
    if bh > 8:
        work *= 1.0 + (bh - 8) / 32.0  # unroll bloat past one sublane tile
    return work


def heuristic_paged(q_shape, page_size, max_pages, dtype):
    """Cost-model argmin head block + backend choice for one decode
    shape. Short contexts (a page or two) lose the kernel's grid setup
    to XLA's fused gather+softmax; past that the paged kernel avoids
    materializing the gathered (B, T, H, D) stream every step."""
    _, h, d = q_shape
    best, best_cost = None, math.inf
    for bh in paged_candidates(h, d, page_size, dtype):
        c = paged_cost(h, d, page_size, max_pages, bh)
        if c < best_cost:
            best, best_cost = bh, c
    backend = "pallas" if page_size * max_pages >= 256 else "xla"
    return {"backend": backend, "block_h": best, "source": "heuristic",
            "score": round(best_cost, 3)}


def resolve_paged(q_shape, page_size, max_pages, dtype):
    """The per-call decision the paged decode kernel consumes: table
    hit, else the cost model, recorded under the decode-shape bucket.
    Decode dispatches happen inside the jitted serving step (tracers —
    nothing to time), so unlike the flash kernel there is no inline
    measurement path: measured entries arrive via offline sweeps writing
    the table, and are never downgraded by this heuristic re-record.
    ``MXT_TUNE_MODE=off`` bypasses the table (pure cost model), matching
    the flash kernel's legacy-global semantics."""
    if _mode() == "off":
        return heuristic_paged(q_shape, page_size, max_pages, dtype)
    tab = _table_mod.table()
    key = _table_mod.paged_key(q_shape, page_size, max_pages, dtype)
    ent = tab.lookup(key)
    if ent is not None:
        return ent
    return tab.record(key, heuristic_paged(q_shape, page_size, max_pages,
                                           dtype))


# --------------------------------------------------------------------------
# quantized-vs-float decode matmuls (weight-only int8 serving)
# --------------------------------------------------------------------------
def quant_cost(k, n, backend):
    """Deterministic relative cost of one (k, n) decode matmul on one
    backend. Decode matmuls are weight-BYTES-bound (batch is a handful
    of slots, the weight tile is read once per launch): float charges
    4 bytes/element; int8 charges 1 byte/element + the per-column amax
    plane + a dequant-epilogue tax + a fixed kernel-setup overhead that
    keeps tiny layers on the fused float path."""
    if backend == "int8":
        return 1.0 * k * n + 4.0 * n + 0.25 * k * n + 2048.0
    return 4.0 * k * n


def heuristic_quant(op, k, n, dtype):
    """Cost-model backend choice for one decode-matmul shape bucket:
    'int8' (weight-only-quantized kernel) when the quantized bytes +
    dequant tax undercut the float weight read, else 'fp'."""
    del op, dtype
    ci, cf = quant_cost(k, n, "int8"), quant_cost(k, n, "fp")
    backend = "int8" if ci < cf else "fp"
    return {"backend": backend, "source": "heuristic",
            "score": round(min(ci, cf), 3)}


def resolve_quant(op, k, n, dtype):
    """The per-shape quantized-vs-float decision a serving engine's
    weight quantization consults (TinyDecoder.quantize_params): table
    hit, else the cost model, recorded under the pow2 (k, n) bucket.
    Like resolve_paged there is no inline measurement (the decision is
    made at engine build, not dispatch) — measured entries arrive via
    offline sweeps writing the table and are never downgraded here.
    ``MXT_TUNE_MODE=off`` bypasses the table entirely."""
    if _mode() == "off":
        return heuristic_quant(op, k, n, dtype)
    tab = _table_mod.table()
    key = _table_mod.quant_key(op, k, n, dtype)
    ent = tab.lookup(key)
    if ent is not None:
        return ent
    return tab.record(key, heuristic_quant(op, k, n, dtype))


# --------------------------------------------------------------------------
# BN backward
# --------------------------------------------------------------------------
def bn_candidates(m, c):
    """Legal block_rows values for a (M, C) BN backward: sublane
    multiples, bounded by the padded row count and a per-buffer VMEM
    budget (two f32 (bm, C) buffers resident per pass)."""
    m8 = _round8(m)
    out = []
    for bm in (8, 16, 32, 64, 128, 256, 512, 1024):
        bm = min(bm, m8)
        if 2 * bm * int(c) * 4 > _VMEM_BUDGET // 2:
            continue
        if bm not in out:
            out.append(bm)
    return out or [_SUBLANE]


def bn_cost(m, c, bm):
    pm = _pad_to(m, bm)
    cost = 1.0 * pm * c
    cost *= 1.0 + 0.004 * (pm // bm)
    if bm < 64:
        cost *= 1.0 + (64 - bm) / 256.0
    return cost


def heuristic_bn(m, c, dtype):
    """Cost-model block_rows; backend stays 'xla' until a measurement
    says otherwise (the round-2 lesson: interpret-green Pallas is not
    Mosaic-green, so the fused BN backward is opt-in per shape via
    measured entries or the MXT_BN_PALLAS global override)."""
    best, best_cost = None, math.inf
    for bm in bn_candidates(m, c):
        cc = bn_cost(m, c, bm)
        if cc < best_cost:
            best, best_cost = bm, cc
    return {"backend": "xla", "block_rows": best,
            "source": "heuristic", "score": round(best_cost, 3)}


def measure_bn(x2d, dy2d, mean, inv, g, interpret=False, iters=None,
               candidates=None):
    """Time candidate block_rows for the fused BN backward plus the XLA
    custom-VJP formulas; returns the winning entry dict."""
    import jax.numpy as jnp

    from ..ops import bn_pallas

    iters = iters or int(_config().get("MXT_TUNE_ITERS"))
    m, c = x2d.shape
    timings = {}
    for bm in (candidates or bn_candidates(m, c)):
        try:
            def run(bm=bm):
                return bn_pallas.bn_bwd_pallas(
                    x2d, dy2d, mean, inv, g, interpret=interpret,
                    block_rows=bm)
            timings[("pallas", bm)] = _time(run, iters)
        except Exception:  # noqa: BLE001
            continue

    def ref():
        dy = dy2d.astype(jnp.float32)
        xhat = (x2d.astype(jnp.float32) - mean.reshape(1, c)) \
            * inv.reshape(1, c)
        db = jnp.sum(dy, axis=0)
        dg = jnp.sum(dy * xhat, axis=0)
        dx = (g.reshape(1, c) * inv.reshape(1, c)) * (
            dy - db.reshape(1, c) / m - xhat * dg.reshape(1, c) / m)
        return dx, dg, db
    timings[("xla", 0)] = _time(ref, iters)

    (backend, bm), score = min(timings.items(), key=lambda kv: kv[1])
    return {"backend": backend, "block_rows": bm,
            "source": "measured", "score": round(score * 1e3, 6)}


# --------------------------------------------------------------------------
# shared timing loop
# --------------------------------------------------------------------------
def _block(res):
    """Synchronize a result pytree (measurement only — never hot path)."""
    import jax

    for leaf in jax.tree_util.tree_leaves(res):
        if hasattr(leaf, "block_until_ready"):  # sync-ok: measurement loop
            leaf.block_until_ready()  # sync-ok: autotuner measurement loop


def _time(fn, iters):
    """Median-of-iters wall time of ``fn`` after one warm (compile)
    call. Median resists the one-off scheduling hiccup that would
    otherwise misrank close candidates."""
    _block(fn())  # compile + warm  # sync-ok: autotuner measurement loop
    samples = []
    for _ in range(max(1, iters)):
        t0 = time.perf_counter()
        _block(fn())
        samples.append(time.perf_counter() - t0)
    samples.sort()
    return samples[len(samples) // 2]


# --------------------------------------------------------------------------
# resolution: table -> measure/heuristic -> record
# --------------------------------------------------------------------------
def _mode():
    return str(_config().get("MXT_TUNE_MODE")).lower()


def _may_measure(arrays):
    """Measurement needs concrete arrays (not tracers — inside a jit
    trace there is nothing to time) and an allowing mode: 'measure'
    anywhere, 'auto' only on a real TPU."""
    import jax

    mode = _mode()
    if mode == "measure":
        allowed = True
    elif mode == "auto":
        allowed = jax.default_backend() in ("tpu", "axon")
    else:
        return False
    if not allowed:
        return False
    return not any(isinstance(a, jax.core.Tracer)
                   for a in arrays if a is not None)


def resolve_attention(q_shape, kv_len, dtype, causal, arrays=None):
    """The per-call decision the flash kernel consumes: table hit, else
    measure (when allowed) or cost model, recorded either way."""
    tab = _table_mod.table()
    key = _table_mod.attn_key(q_shape, kv_len, dtype, causal)
    ent = tab.lookup(key)
    if ent is not None:
        return ent
    if arrays is not None and _may_measure(arrays):
        import jax

        q, k, v, bias, sm_scale = arrays
        ent = measure_attention(
            q, k, v, bias, causal, sm_scale,
            interpret=jax.default_backend() not in ("tpu", "axon"))
    else:
        ent = heuristic_attention(q_shape, kv_len, dtype, causal)
    return tab.record(key, ent)


def resolve_bn(m, c, dtype, arrays=None):
    tab = _table_mod.table()
    key = _table_mod.bn_key(m, c, dtype)
    ent = tab.lookup(key)
    if ent is not None:
        return ent
    if arrays is not None and _may_measure(arrays):
        import jax

        x2d, dy2d, mean, inv, g = arrays
        ent = measure_bn(
            x2d, dy2d, mean, inv, g,
            interpret=jax.default_backend() not in ("tpu", "axon"))
    else:
        ent = heuristic_bn(m, c, dtype)
    return tab.record(key, ent)
