"""Kernel autotuning + compile infrastructure (ROADMAP direction 3).

The MXNet heritage is ``MXNET_CUDNN_AUTOTUNE_DEFAULT`` — first call per
shape races the candidate algos, the winner is memoized. Here the same
idea covers what a TPU build actually tunes:

- **Block configs per shape** for the Pallas kernels (flash attention's
  (block_q, block_k), BN backward's block_rows) — searched over
  tiling-legal candidates by timed micro-benchmarks on device, or by a
  deterministic cost model on CPU/CI (autotune.py).
- **XLA-vs-Pallas per shape** — the per-call replacement for the global
  ``MXT_BN_PALLAS`` / reference-path switches.
- **A versioned persistent table** (table.py, ``MXT_TUNE_TABLE``) so
  decisions and recorded shape signatures survive the process.
- **Persistent compile cache + AOT warm-start** (compile_cache.py,
  warmup.py, ``MXT_COMPILE_CACHE_DIR``): ``tuning.warmup()`` compiles
  the canonical entry points ahead of the hot path; a second process
  replays compiles from disk — zero hot-path JIT on resume.

Telemetry: ``mxt_compile_seconds{phase}``, ``mxt_compiles_total``,
``mxt_compile_cache_{hits,misses}_total``,
``mxt_tune_cache_{hits,misses}_total``, ``mxt_warmup_seconds``.
"""
from __future__ import annotations

from . import autotune, compile_cache, table as _table_mod, warmup as _warmup
from .autotune import (attention_candidates, attention_cost, bn_candidates,
                       bn_cost, heuristic_attention, heuristic_bn,
                       heuristic_paged, heuristic_quant,
                       measure_attention, measure_bn,
                       paged_candidates, paged_cost, quant_cost,
                       resolve_attention, resolve_bn, resolve_paged,
                       resolve_quant)
from .compile_cache import (cache_dir, compile_stats, install_listeners,
                            setup as setup_compile_cache)
from .table import (TABLE_VERSION, TuneTable, attn_key, bn_key, device_kind,
                    paged_key, quant_key, reset, save, table)
from .warmup import record_signature, register_step, signatures, warmup

__all__ = [
    "attention_candidates", "attention_cost", "bn_candidates", "bn_cost",
    "heuristic_attention", "heuristic_bn", "heuristic_paged",
    "heuristic_quant",
    "measure_attention", "measure_bn", "paged_candidates", "paged_cost",
    "quant_cost",
    "resolve_attention", "resolve_bn", "resolve_paged", "resolve_quant",
    "cache_dir", "compile_stats", "install_listeners",
    "setup_compile_cache",
    "TABLE_VERSION", "TuneTable", "attn_key", "bn_key", "device_kind",
    "paged_key", "quant_key", "reset", "save", "table",
    "record_signature", "register_step", "signatures", "warmup",
    "autotune", "compile_cache",
]

# passive compile observability + persistent cache activation when the
# env asks for it — importing mxnet_tpu is enough to start counting
install_listeners()
setup_compile_cache()
