"""kvstore server-role entry (ref: python/mxnet/kvstore_server.py — the
process that blocks in MXKVStoreRunServer under DMLC_ROLE=server).

The TPU build has no SEPARATE parameter-server process role for *sync*
training: gradient exchange compiles into the training step as XLA
collectives over ICI/DCN (SURVEY §2.4 — the worker/server topology
collapses into SPMD), and ``tools/launch.py`` starts only workers. Two
surfaces do need a server and both are the SAME one — the
membership-enabled async server (async_server.py): ``dist_async``
hogwild runs it as a thread inside worker 0, and this module now hosts
it standalone for deployments that want the membership/elasticity
coordinator (heartbeats, stale-push fencing, rejoin snapshots —
membership.py) to outlive any single worker::

    MXT_COORDINATOR=host:port python -m mxnet_tpu.kvstore_server

Without ``MXT_COORDINATOR`` there is still nothing to serve, and
construction fails with the design explanation instead of an
ImportError.
"""
from __future__ import annotations

import os
import threading

from .base import MXNetError

__all__ = ["KVStoreServer", "_init_kvstore_server_module", "main"]


class KVStoreServer:
    """ref: kvstore_server.py — KVStoreServer. ``run()`` hosts the
    membership-enabled async parameter server at the address derived
    from ``MXT_COORDINATOR`` and blocks until :meth:`close` (or the
    server is torn down). Constructible only when a coordinator is
    configured — otherwise the TPU build has, by design, nothing to
    serve."""

    def __init__(self, kvstore=None):
        del kvstore  # reference parity: the C handle is meaningless here
        from . import async_server

        self._addr = async_server.server_address()
        if self._addr is None:
            raise MXNetError(
                "the TPU build has no separate parameter-server process "
                "for sync training: SPMD collectives are compiled into "
                "the step (parallel.ShardedTrainStep), and dist_async's "
                "hogwild + membership server runs as a thread inside "
                "worker 0 (async_server.py). To host that server "
                "standalone, set MXT_COORDINATOR=host:port and run "
                "`python -m mxnet_tpu.kvstore_server`.")
        self._server = None
        self._stop = threading.Event()

    def run(self):
        """Serve until close(): binds the membership/async server (store
        ops + register/heartbeat/barrier/reduce + the sharded embedding
        table ops) on the coordinator's async port and parks this
        thread. ``MXT_EMBEDDING_SNAPSHOT_DIR`` makes the embedding
        shard durable across restarts; ``MXT_EMBEDDING_SERVER_ID`` (+
        optionally ``MXT_EMBEDDING_COORDINATOR=host:port``) registers
        this process in the fleet's membership table so client rings
        discover it."""
        from . import async_server, embedding

        host, port = self._addr
        self._server = async_server.get_server(host, port)
        sid = os.environ.get("MXT_EMBEDDING_SERVER_ID")
        store = embedding.EmbeddingStore(
            snapshot_dir=os.environ.get("MXT_EMBEDDING_SNAPSHOT_DIR"),
            server_id=int(sid) if sid is not None else None)
        self._server.attach_embedding(store)
        self._emb_member = None
        if sid is not None:
            handle = embedding.LocalEmbeddingServer(
                int(sid), host, port, self._server, store)
            coord = os.environ.get("MXT_EMBEDDING_COORDINATOR")
            if coord and ":" in coord:
                chost, _, cport = coord.rpartition(":")
                handle.register((chost, int(cport)))
            else:
                # coordinator-less fleet: this server IS the registry
                handle.register((host, port))
            self._emb_member = handle.member
        print("KVSTORE_SERVER_READY %s:%d" % (host, port), flush=True)
        try:
            while not self._server._stop.is_set() \
                    and not self._stop.wait(0.2):
                pass
        except KeyboardInterrupt:
            pass
        finally:
            if self._emb_member is not None:
                self._emb_member.stop(deregister=True)
            self._server.close()

    def close(self):
        self._stop.set()


def _init_kvstore_server_module():
    """ref: kvstore_server.py — called at import under DMLC_ROLE=server
    (the reference blocks in the server loop there). With a coordinator
    configured the role is now real — serving happens via
    ``python -m mxnet_tpu.kvstore_server`` — so only a coordinator-less
    reference-style launch fails fast with the design explanation."""
    role = os.environ.get("DMLC_ROLE", "")
    if role in ("server", "scheduler") \
            and not os.environ.get("MXT_COORDINATOR"):
        raise MXNetError(
            "DMLC_ROLE=%s detected without MXT_COORDINATOR: reference-"
            "style parameter-server launches are not used by the TPU "
            "build. Use tools/launch.py (workers only; rendezvous via "
            "MXT_COORDINATOR), or host the membership/async server with "
            "`MXT_COORDINATOR=host:port python -m "
            "mxnet_tpu.kvstore_server`." % role)


# match the reference's import-time behavior: a server/scheduler-role
# process must not silently proceed as a worker
_init_kvstore_server_module()


def main():
    KVStoreServer().run()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
