"""kvstore server-role entry (ref: python/mxnet/kvstore_server.py — the
process that blocks in MXKVStoreRunServer under DMLC_ROLE=server).

The TPU build has no SEPARATE parameter-server process role: synchronous
gradient exchange is compiled into the training step as XLA collectives
over ICI/DCN (SURVEY §2.4 — the worker/server topology collapses into
SPMD), and ``tools/launch.py`` starts only workers. The one surface that
does need a server — ``dist_async`` hogwild — runs as a THREAD inside
worker 0 (see async_server.py), so there is still nothing to launch on a
dedicated server node. This module keeps the import surface so
reference-style launches fail with an explanation instead of an
ImportError.
"""
from __future__ import annotations

import os

from .base import MXNetError

__all__ = ["KVStoreServer", "_init_kvstore_server_module"]


class KVStoreServer:
    """ref: kvstore_server.py — KVStoreServer. Not instantiable here."""

    def __init__(self, kvstore):
        raise MXNetError(
            "the TPU build has no separate parameter-server process: "
            "sync dist training uses SPMD collectives compiled into the "
            "step (parallel.ShardedTrainStep), and dist_async's hogwild "
            "server runs as a thread inside worker 0 (async_server.py). "
            "Launch workers only — nothing runs on a server node.")

    def run(self):  # pragma: no cover - unreachable (init raises)
        raise NotImplementedError


def _init_kvstore_server_module():
    """ref: kvstore_server.py — called at import under DMLC_ROLE=server
    (the reference blocks in the server loop there; here a stale
    reference-style launch fails fast with the design explanation)."""
    role = os.environ.get("DMLC_ROLE", "")
    if role == "server" or role == "scheduler":
        raise MXNetError(
            "DMLC_ROLE=%s detected: reference-style parameter-server "
            "launches are not used by the TPU build. Use tools/launch.py "
            "(workers only; rendezvous via MXT_COORDINATOR)." % role)


# match the reference's import-time behavior: a server/scheduler-role
# process must not silently proceed as a worker
_init_kvstore_server_module()
