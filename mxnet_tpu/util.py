"""Misc utilities (ref: python/mxnet/util.py). The reference's numpy-
semantics shims (use_np_shape / use_np_array) toggle global flags that
alter NDArray behavior; this build's NDArray already follows numpy
zero-size/zero-dim semantics natively (jax.numpy underneath), so the
toggles are accepted for API parity and are no-ops, documented as such.
"""
from __future__ import annotations

import functools
import os

__all__ = ["makedirs", "use_np_shape", "np_shape", "is_np_shape",
           "use_np_array", "np_array", "is_np_array", "use_np",
           "get_cuda_compute_capability"]


def makedirs(d):
    """mkdir -p (ref: util.py — makedirs; py2 compat shim upstream)."""
    os.makedirs(os.path.expanduser(d), exist_ok=True)


def is_np_shape():
    """Always True: numpy shape semantics (0-dim/0-size arrays) are
    native to this build (ref: util.py — is_np_shape)."""
    return True


def is_np_array():
    """Always True — see module docstring."""
    return True


class _NoOpScope:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def np_shape(active=True):
    """No-op scope for API parity (ref: util.py — np_shape)."""
    del active
    return _NoOpScope()


def np_array(active=True):
    """No-op scope for API parity (ref: util.py — np_array)."""
    del active
    return _NoOpScope()


def use_np_shape(func):
    """Decorator form, identity here (ref: util.py — use_np_shape)."""
    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        return func(*args, **kwargs)
    return wrapper


use_np_array = use_np_shape
use_np = use_np_shape


def get_cuda_compute_capability(ctx=None):
    """No CUDA in the TPU build (ref: util.py) — explicit error beats a
    silent wrong answer."""
    raise RuntimeError("CUDA is not available in the TPU build")
