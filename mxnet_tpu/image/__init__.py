"""``mx.image`` — pure-python image pipeline
(ref: python/mxnet/image/image.py)."""
from .image import (  # noqa: F401
    imdecode, imread, imresize, resize_short, fixed_crop, random_crop,
    center_crop, color_normalize, scale_down,
    Augmenter, ResizeAug, ForceResizeAug, RandomCropAug, CenterCropAug,
    HorizontalFlipAug, CastAug, ColorNormalizeAug, BrightnessJitterAug,
    ContrastJitterAug, SaturationJitterAug, HueJitterAug, LightingAug,
    RandomGrayAug, RandomOrderAug, ColorJitterAug, CreateAugmenter,
    ImageIter,
)
from .detection import (  # noqa: F401
    DetAugmenter, DetBorrowAug, DetRandomSelectAug, DetHorizontalFlipAug,
    DetRandomCropAug, DetRandomPadAug, CreateMultiRandCropAugmenter,
    CreateDetAugmenter, ImageDetIter,
)
