"""Pure-python image loading + augmentation (ref:
python/mxnet/image/image.py — the python alternative to the C++
ImageRecordIter; same function/class names and HWC uint8/float semantics).

Decode runs through PIL (the reference wraps OpenCV via the imdecode op);
augmenters operate on HWC numpy/NDArray, and ImageIter batches to NCHW —
device transfer happens once per batch, which is the TPU-friendly split
(host-side per-image work, one device_put per batch).
"""
from __future__ import annotations

import io as _io
import os
import random as _pyrandom

import numpy as np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..io.io import DataBatch, DataDesc, DataIter


def _to_np(img):
    if isinstance(img, NDArray):
        return img.asnumpy()
    return np.asarray(img)


def imdecode(buf, flag=1, to_rgb=True):
    """Decode an encoded image buffer to an HWC uint8 NDArray
    (ref: image.imdecode over the cv::imdecode op)."""
    from PIL import Image

    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    img = Image.open(_io.BytesIO(bytes(buf)))
    img = img.convert("RGB" if flag else "L")
    arr = np.asarray(img, dtype=np.uint8)
    if not to_rgb and flag:
        arr = arr[..., ::-1]  # BGR like OpenCV default
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return NDArray(arr)


def imread(filename, flag=1, to_rgb=True):
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w, h, interp=2):
    """Resize HWC image to (h, w) (ref: image.imresize)."""
    from PIL import Image

    arr = _to_np(src)
    pil = Image.fromarray(arr.astype(np.uint8).squeeze()
                          if arr.shape[-1] == 1 else arr.astype(np.uint8))
    resample = {0: Image.NEAREST, 1: Image.BILINEAR, 2: Image.BICUBIC,
                3: Image.NEAREST, 4: Image.LANCZOS}.get(interp,
                                                        Image.BILINEAR)
    out = np.asarray(pil.resize((w, h), resample), dtype=np.uint8)
    if out.ndim == 2:
        out = out[:, :, None]
    return NDArray(out)


def scale_down(src_size, size):
    """Shrink (w, h) to fit inside src_size keeping aspect
    (ref: image.scale_down)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    """Resize so the shorter edge is ``size`` (ref: image.resize_short)."""
    arr = _to_np(src)
    h, w = arr.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(arr, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    arr = _to_np(src)[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return imresize(arr, size[0], size[1], interp)
    return NDArray(arr)


def random_crop(src, size, interp=2):
    """Random crop to (w, h); returns (img, (x0, y0, w, h))
    (ref: image.random_crop)."""
    arr = _to_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = _pyrandom.randint(0, w - new_w)
    y0 = _pyrandom.randint(0, h - new_h)
    out = fixed_crop(arr, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    arr = _to_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(arr, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    arr = _to_np(src).astype(np.float32)
    arr = arr - np.asarray(mean, np.float32)
    if std is not None:
        arr = arr / np.asarray(std, np.float32)
    return NDArray(arr)


# ---------------------------------------------------------------------------
# augmenters (ref: image.py Augmenter classes; each is callable HWC->HWC)
# ---------------------------------------------------------------------------
class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return NDArray(_to_np(src)[:, ::-1].copy())
        return src if isinstance(src, NDArray) else NDArray(_to_np(src))


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(typ=typ)
        self.typ = typ

    def __call__(self, src):
        return NDArray(_to_np(src).astype(self.typ))


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean, self.std = mean, std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        return NDArray(_to_np(src).astype(np.float32) * alpha)


class ContrastJitterAug(Augmenter):
    _coef = np.array([0.299, 0.587, 0.114], np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        arr = _to_np(src).astype(np.float32)
        alpha = 1.0 + _pyrandom.uniform(-self.contrast, self.contrast)
        gray = (arr * self._coef).sum(-1).mean()
        return NDArray(arr * alpha + gray * (1 - alpha))


class SaturationJitterAug(Augmenter):
    _coef = np.array([0.299, 0.587, 0.114], np.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        arr = _to_np(src).astype(np.float32)
        alpha = 1.0 + _pyrandom.uniform(-self.saturation, self.saturation)
        gray = (arr * self._coef).sum(-1, keepdims=True)
        return NDArray(arr * alpha + gray * (1 - alpha))


class HueJitterAug(Augmenter):
    """Rotate the color cube around the gray axis by a random angle —
    the YIQ-space hue approximation (ref: image.py — HueJitterAug)."""

    _t_yiq = np.array([[0.299, 0.587, 0.114],
                       [0.596, -0.274, -0.321],
                       [0.211, -0.523, 0.311]], np.float32)
    _t_rgb = np.linalg.inv(_t_yiq).astype(np.float32)

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        arr = _to_np(src).astype(np.float32)
        alpha = _pyrandom.uniform(-self.hue, self.hue)
        u, v = np.cos(alpha * np.pi), np.sin(alpha * np.pi)
        rot = np.array([[1, 0, 0], [0, u, -v], [0, v, u]], np.float32)
        m = self._t_rgb @ rot @ self._t_yiq
        return NDArray(arr @ m.T)


class LightingAug(Augmenter):
    """AlexNet-style PCA color noise (ref: image.py — LightingAug)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, np.float32)
        self.eigvec = np.asarray(eigvec, np.float32)

    def __call__(self, src):
        arr = _to_np(src).astype(np.float32)
        alpha = np.random.normal(0, self.alphastd, size=(3,))
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return NDArray(arr + rgb.astype(np.float32))


class RandomGrayAug(Augmenter):
    """Replace the image with its luma with probability p
    (ref: image.py — RandomGrayAug)."""

    _coef = np.array([[0.299], [0.587], [0.114]], np.float32)

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            arr = _to_np(src).astype(np.float32)
            gray = arr @ self._coef  # (H, W, 1)
            return NDArray(np.broadcast_to(gray, arr.shape).copy())
        return src


class RandomOrderAug(Augmenter):
    """Apply child augmenters in a fresh random order each call
    (ref: image.py — RandomOrderAug)."""

    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def __call__(self, src):
        order = list(self.ts)
        _pyrandom.shuffle(order)
        for t in order:
            src = t(src)
        return src


def ColorJitterAug(brightness, contrast, saturation):
    """Brightness/contrast/saturation jitter in random order
    (ref: image.py — ColorJitterAug)."""
    ts = []
    if brightness > 0:
        ts.append(BrightnessJitterAug(brightness))
    if contrast > 0:
        ts.append(ContrastJitterAug(contrast))
    if saturation > 0:
        ts.append(SaturationJitterAug(saturation))
    return RandomOrderAug(ts)


# ImageNet PCA eigen-decomposition used by the reference's train scripts
_PCA_EIGVAL = np.array([55.46, 4.794, 1.148], np.float32)
_PCA_EIGVEC = np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.8140],
                        [-0.5836, -0.6948, 0.4203]], np.float32)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Standard augmenter list builder (ref: image.CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        auglist.append(LightingAug(pca_noise, _PCA_EIGVAL, _PCA_EIGVEC))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


# ---------------------------------------------------------------------------
# ImageIter (ref: image.py — ImageIter; .rec or .lst/imglist driven)
# ---------------------------------------------------------------------------
class ImageIter(DataIter):
    """Image iterator with augmenters, reading an imglist / .lst file /
    indexed .rec (ref: image.ImageIter). Yields NCHW float batches."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imgidx=None, path_imglist=None,
                 path_root=None, shuffle=False, aug_list=None,
                 imglist=None, data_name="data",
                 label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        if len(data_shape) != 3 or data_shape[0] != 3:
            raise MXNetError("data_shape must be (3, H, W)")
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self.aug_list = CreateAugmenter(data_shape, **kwargs) \
            if aug_list is None else aug_list
        self._data_name = data_name
        self._label_name = label_name

        self._rec = None
        self.imglist = {}
        if path_imgrec is not None:
            from ..recordio import MXIndexedRecordIO
            idx_path = path_imgidx or \
                os.path.splitext(path_imgrec)[0] + ".idx"
            self._rec = MXIndexedRecordIO(idx_path, path_imgrec, "r")
            self.seq = list(self._rec.keys)
        else:
            if imglist is None:
                if path_imglist is None:
                    raise MXNetError(
                        "ImageIter needs path_imgrec, path_imglist, or "
                        "imglist")
                imglist = []
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        # .lst format: index \t label... \t relpath
                        labels = [float(x) for x in parts[1:-1]]
                        imglist.append([labels if len(labels) > 1
                                        else labels[0], parts[-1]])
            for i, (label, fname) in enumerate(imglist):
                self.imglist[i] = (np.atleast_1d(
                    np.asarray(label, np.float32)), fname)
            self.seq = list(self.imglist)
            self.path_root = path_root or "."
        self.cursor = 0
        if self.shuffle:
            _pyrandom.shuffle(self.seq)

    @property
    def provide_data(self):
        return [DataDesc(self._data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc(self._label_name, shape)]

    def reset(self):
        self.cursor = 0
        if self.shuffle:
            _pyrandom.shuffle(self.seq)

    def next_sample(self):
        if self.cursor >= len(self.seq):
            raise StopIteration
        key = self.seq[self.cursor]
        self.cursor += 1
        if self._rec is not None:
            from ..recordio import unpack
            header, img_bytes = unpack(self._rec.read_idx(key))
            label = np.atleast_1d(np.asarray(header.label, np.float32))
            return label, img_bytes
        label, fname = self.imglist[key]
        with open(os.path.join(self.path_root, fname), "rb") as f:
            return label, f.read()

    def next(self):
        c, h, w = self.data_shape
        data = np.zeros((self.batch_size, h, w, c), np.float32)
        labels = np.zeros((self.batch_size, self.label_width), np.float32)
        i = 0
        pad = 0
        while i < self.batch_size:
            try:
                label, img_bytes = self.next_sample()
            except StopIteration:
                if i == 0:
                    raise
                pad = self.batch_size - i
                break
            img = imdecode(img_bytes)
            for aug in self.aug_list:
                img = aug(img)
            arr = _to_np(img)
            if arr.shape[:2] != (h, w):
                arr = _to_np(imresize(arr, w, h))
            data[i] = arr.astype(np.float32)
            labels[i] = label[:self.label_width]
            i += 1
        batch_data = NDArray(np.transpose(data, (0, 3, 1, 2)))
        lab = labels[:, 0] if self.label_width == 1 else labels
        return DataBatch(data=[batch_data], label=[NDArray(lab)], pad=pad)
