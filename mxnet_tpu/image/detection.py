"""Detection-aware image pipeline (ref: python/mxnet/image/detection.py —
Det*Aug augmenter classes + CreateDetAugmenter + ImageDetIter).

Label convention matches the reference: each object is a row
``[class_id, xmin, ymin, xmax, ymax, ...]`` with coordinates normalized
to [0, 1]; a batch label is (B, max_objects, label_width), short images
padded with class_id -1 rows.
"""
from __future__ import annotations

import random as _pyrandom

import numpy as np

from ..base import MXNetError
from ..io.io import DataBatch, DataDesc
from ..ndarray.ndarray import NDArray
from .image import Augmenter, ImageIter, _to_np, imdecode, imresize

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateMultiRandCropAugmenter", "CreateDetAugmenter",
           "ImageDetIter"]


class DetAugmenter:
    """Detection augmenter: transforms (image, label) jointly
    (ref: detection.py — DetAugmenter)."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift an image-only Augmenter into the detection pipeline
    (ref: detection.py — DetBorrowAug). Only geometry-preserving
    augmenters (color/cast/normalize) are safe to borrow."""

    def __init__(self, augmenter):
        if not isinstance(augmenter, Augmenter):
            raise MXNetError("DetBorrowAug needs an image Augmenter")
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly pick one of several augmenters (or skip)
    (ref: detection.py — DetRandomSelectAug)."""

    def __init__(self, aug_list, skip_prob=0.0):
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if _pyrandom.random() < self.skip_prob or not self.aug_list:
            return src, label
        return _pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Flip image + x-coordinates with probability p
    (ref: detection.py — DetHorizontalFlipAug)."""

    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() < self.p:
            src = _to_np(src)[:, ::-1, :]
            label = label.copy()
            valid = label[:, 0] >= 0
            x1 = label[valid, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - x1
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop with a minimum-object-coverage constraint
    (ref: detection.py — DetRandomCropAug: _check_satisfy_constraints /
    _update_labels): up to max_attempts candidate crops are sampled; a
    candidate is accepted when it overlaps at least one object AND every
    object it overlaps keeps > min_object_covered of its area inside it
    (min over positive coverages). On accept, objects covered below
    min_eject_coverage are ejected (class -1) and the rest are clipped +
    re-normalized to the crop. If no candidate ever satisfies the
    constraint the input passes through unchanged."""

    def __init__(self, min_object_covered=0.3,
                 aspect_ratio_range=(0.75, 1.33), area_range=(0.3, 1.0),
                 min_eject_coverage=0.3, max_attempts=25):
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def _sample_geometry(self, h, w):
        target_area = _pyrandom.uniform(*self.area_range) * h * w
        ratio = _pyrandom.uniform(*self.aspect_ratio_range)
        cw = int(round(np.sqrt(target_area * ratio)))
        ch = int(round(np.sqrt(target_area / ratio)))
        if cw > w or ch > h:
            return None
        x0 = _pyrandom.randint(0, w - cw)
        y0 = _pyrandom.randint(0, h - ch)
        return x0, y0, cw, ch

    @staticmethod
    def _coverage(boxes, nx0, ny0, nx1, ny1):
        ix0 = np.maximum(boxes[:, 0], nx0)
        iy0 = np.maximum(boxes[:, 1], ny0)
        ix1 = np.minimum(boxes[:, 2], nx1)
        iy1 = np.minimum(boxes[:, 3], ny1)
        inter = np.maximum(ix1 - ix0, 0) * np.maximum(iy1 - iy0, 0)
        area = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
        return np.where(area > 0, inter / np.maximum(area, 1e-12), 0)

    def __call__(self, src, label):
        img = _to_np(src)
        h, w = img.shape[:2]
        valid = label[:, 0] >= 0
        boxes = label[valid, 1:5]
        if not len(boxes):
            # no valid objects -> the coverage constraint can never hold
            # (reference _check_satisfy_constraints returns False on an
            # empty coverage set), so background-only samples pass through
            return img, label
        for _ in range(self.max_attempts):
            geom = self._sample_geometry(h, w)
            if geom is None:
                continue  # geometry didn't fit — counts as an attempt
            x0, y0, cw, ch = geom
            nx0, ny0 = x0 / w, y0 / h
            nx1, ny1 = (x0 + cw) / w, (y0 + ch) / h
            cover = self._coverage(boxes, nx0, ny0, nx1, ny1)
            overlapping = cover > 0
            if not overlapping.any() or \
                    cover[overlapping].min() <= self.min_object_covered:
                continue  # constraint failed — try another candidate
            keep = cover >= self.min_eject_coverage
            if not keep.any():
                continue
            out = label.copy()
            nb = np.stack([
                (np.clip(boxes[:, 0], nx0, nx1) - nx0) / (nx1 - nx0),
                (np.clip(boxes[:, 1], ny0, ny1) - ny0) / (ny1 - ny0),
                (np.clip(boxes[:, 2], nx0, nx1) - nx0) / (nx1 - nx0),
                (np.clip(boxes[:, 3], ny0, ny1) - ny0) / (ny1 - ny0),
            ], axis=1)
            rows = np.where(valid)[0]
            out[rows, 1:5] = nb
            out[rows[~keep], 0] = -1  # ejected objects
            return img[y0:y0 + ch, x0:x0 + cw], out
        return img, label


def _pair_list(x):
    """Normalize a (lo, hi) pair or a sequence of pairs to a list of
    pairs — the crop/pad constraint arguments accept both forms (the
    SSD recipe passes per-sampler lists)."""
    if isinstance(x, (list, tuple)) and len(x) and np.ndim(x[0]) > 0:
        return [tuple(p) for p in x]
    return [tuple(x)]


class DetRandomPadAug(DetAugmenter):
    """Pad to a random larger canvas, boxes shrink accordingly
    (ref: detection.py — DetRandomPadAug)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=25, pad_val=127):
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        img = _to_np(src)
        h, w = img.shape[:2]
        for _ in range(self.max_attempts):
            scale = _pyrandom.uniform(*self.area_range)
            ratio = _pyrandom.uniform(*self.aspect_ratio_range)
            nw = int(round(np.sqrt(scale * h * w * ratio)))
            nh = int(round(np.sqrt(scale * h * w / ratio)))
            if nw >= w and nh >= h:
                x0 = _pyrandom.randint(0, nw - w)
                y0 = _pyrandom.randint(0, nh - h)
                canvas = np.full((nh, nw, img.shape[2]), self.pad_val,
                                 img.dtype)
                canvas[y0:y0 + h, x0:x0 + w] = img
                out = label.copy()
                valid = out[:, 0] >= 0
                out[valid, 1] = (out[valid, 1] * w + x0) / nw
                out[valid, 3] = (out[valid, 3] * w + x0) / nw
                out[valid, 2] = (out[valid, 2] * h + y0) / nh
                out[valid, 4] = (out[valid, 4] * h + y0) / nh
                return canvas, out
        return img, label


def CreateMultiRandCropAugmenter(min_object_covered=0.1,
                                 aspect_ratio_range=(0.75, 1.33),
                                 area_range=(0.05, 1.0),
                                 min_eject_coverage=0.3, max_attempts=50,
                                 skip_prob=0.0):
    """Sampler bank: one DetRandomCropAug per parameter set, one picked
    at random per image (ref: detection.py — CreateMultiRandCropAugmenter;
    SSD's canonical config passes lists like min_object_covered=
    [0.1, 0.3, 0.5, 0.7, 0.9]). Scalar arguments broadcast."""

    covered = list(min_object_covered) if isinstance(
        min_object_covered, (list, tuple)) else [min_object_covered]
    n = len(covered)

    def broad(x, pairwise=False):
        # pairwise args are (lo, hi) pairs; a bare pair means "same for
        # every sampler", a sequence of pairs configures each one
        if pairwise:
            vals = _pair_list(x)
            if len(vals) == 1:
                vals = vals * n
        else:
            vals = list(x) if isinstance(x, (list, tuple)) else [x] * n
        if len(vals) != n:
            raise MXNetError(
                "CreateMultiRandCropAugmenter arguments must share one "
                "length, got %d vs %d" % (len(vals), n))
        return vals

    aspects = broad(aspect_ratio_range, pairwise=True)
    areas = broad(area_range, pairwise=True)
    ejects = broad(min_eject_coverage)
    attempts = broad(max_attempts)
    crops = [DetRandomCropAug(c, asp, ar, ej, att)
             for c, asp, ar, ej, att in zip(covered, aspects, areas,
                                            ejects, attempts)]
    return DetRandomSelectAug(crops, skip_prob=skip_prob)


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0,
                       pca_noise=0, hue=0, inter_method=2,
                       min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, pad_val=(127, 127, 127)):
    """Standard detection augmenter chain (ref: detection.py —
    CreateDetAugmenter). rand_crop/rand_pad are application
    probabilities; list-valued crop constraints build a multi-sampler
    bank (the SSD recipe)."""
    auglist = []
    if resize > 0:
        from .image import ResizeAug

        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        # crops never upscale: clamp every sampler's area hi to 1.0
        # (broad() broadcasts a length-1 pair list across samplers)
        crop_area = [(lo, min(1.0, hi)) for lo, hi in _pair_list(area_range)]
        auglist.append(CreateMultiRandCropAugmenter(
            min_object_covered, aspect_ratio_range, crop_area,
            min_eject_coverage, max_attempts,
            skip_prob=1.0 - rand_crop))
    if rand_pad > 0:
        # the padder is a single sampler: envelope any per-sampler lists
        aspect_env = (min(lo for lo, _ in _pair_list(aspect_ratio_range)),
                      max(hi for _, hi in _pair_list(aspect_ratio_range)))
        area_hi = max(hi for _, hi in _pair_list(area_range))
        attempts = max(max_attempts) if isinstance(
            max_attempts, (list, tuple)) else max_attempts
        padder = DetRandomPadAug(aspect_env, (1.0, max(1.0, area_hi)),
                                 attempts, pad_val[0])
        auglist.append(DetRandomSelectAug([padder], 1.0 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    # color/cast augs built directly — CreateAugmenter always appends a
    # CenterCrop to its data_shape, which would destroy the image here
    from .image import (CastAug, ColorJitterAug, ColorNormalizeAug,
                        HueJitterAug, LightingAug, RandomGrayAug,
                        _PCA_EIGVAL, _PCA_EIGVEC)

    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if pca_noise > 0:
        auglist.append(DetBorrowAug(
            LightingAug(pca_noise, _PCA_EIGVAL, _PCA_EIGVEC)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    auglist.append(DetBorrowAug(CastAug()))
    if mean is not None or std is not None:
        mean = np.asarray(mean if mean is not None else (0, 0, 0),
                          np.float32)
        std = np.asarray(std if std is not None else (1, 1, 1), np.float32)
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator: multi-object labels, padded to a fixed
    max-objects width (ref: detection.py — ImageDetIter). Yields data
    (B, 3, H, W) and label (B, max_objects, label_width)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root=None, imglist=None,
                 aug_list=None, label_width=5, max_objects=16, **kwargs):
        # split base-iterator options from augmenter options
        iter_kwargs = {k: kwargs.pop(k) for k in
                       ("shuffle", "path_imgidx", "data_name", "label_name")
                       if k in kwargs}
        if aug_list is not None and kwargs:
            raise MXNetError(
                "augmenter options %s conflict with an explicit aug_list "
                "— put them in the aug_list instead" % sorted(kwargs))
        super().__init__(batch_size, data_shape, label_width=label_width,
                         path_imgrec=path_imgrec,
                         path_imglist=path_imglist, path_root=path_root,
                         imglist=imglist,
                         aug_list=aug_list if aug_list is not None
                         else CreateDetAugmenter(data_shape, **kwargs),
                         **iter_kwargs)
        self.max_objects = max_objects

    @property
    def provide_label(self):
        return [DataDesc(self._label_name,
                         (self.batch_size, self.max_objects,
                          self.label_width))]

    def _parse_label(self, raw):
        """Flat header label -> (max_objects, label_width), padded with
        class -1 rows. Accepts either bare object rows or the reference's
        [header_width, label_width, ...objects] packed form."""
        flat = np.asarray(raw, np.float32).ravel()
        lw = self.label_width
        if flat.size >= 2 and float(flat[0]).is_integer() and \
                flat.size > 2 and (flat.size - int(flat[0])) % lw == 0 \
                and int(flat[1]) == lw:
            flat = flat[int(flat[0]):]  # strip packed header
        n = flat.size // lw
        objs = flat[:n * lw].reshape(n, lw)
        out = np.full((self.max_objects, lw), -1.0, np.float32)
        out[:min(n, self.max_objects)] = objs[:self.max_objects]
        return out

    def next(self):
        c, h, w = self.data_shape
        data = np.zeros((self.batch_size, h, w, c), np.float32)
        labels = np.full((self.batch_size, self.max_objects,
                          self.label_width), -1.0, np.float32)
        i = 0
        pad = 0
        while i < self.batch_size:
            try:
                label, img_bytes = self.next_sample()
            except StopIteration:
                if i == 0:
                    raise
                pad = self.batch_size - i
                break
            img = imdecode(img_bytes)
            lbl = self._parse_label(label)
            for aug in self.aug_list:
                img, lbl = aug(img, lbl)
            arr = _to_np(img)
            if arr.shape[:2] != (h, w):
                arr = _to_np(imresize(arr, w, h))
            data[i] = arr.astype(np.float32)
            labels[i] = lbl
            i += 1
        batch_data = NDArray(np.transpose(data, (0, 3, 1, 2)))
        return DataBatch(data=[batch_data], label=[NDArray(labels)],
                         pad=pad)
