"""Detection-aware image pipeline (ref: python/mxnet/image/detection.py —
Det*Aug augmenter classes + CreateDetAugmenter + ImageDetIter).

Label convention matches the reference: each object is a row
``[class_id, xmin, ymin, xmax, ymax, ...]`` with coordinates normalized
to [0, 1]; a batch label is (B, max_objects, label_width), short images
padded with class_id -1 rows.
"""
from __future__ import annotations

import random as _pyrandom

import numpy as np

from ..base import MXNetError
from ..io.io import DataBatch, DataDesc
from ..ndarray.ndarray import NDArray
from .image import Augmenter, ImageIter, _to_np, imdecode, imresize

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter:
    """Detection augmenter: transforms (image, label) jointly
    (ref: detection.py — DetAugmenter)."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift an image-only Augmenter into the detection pipeline
    (ref: detection.py — DetBorrowAug). Only geometry-preserving
    augmenters (color/cast/normalize) are safe to borrow."""

    def __init__(self, augmenter):
        if not isinstance(augmenter, Augmenter):
            raise MXNetError("DetBorrowAug needs an image Augmenter")
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly pick one of several augmenters (or skip)
    (ref: detection.py — DetRandomSelectAug)."""

    def __init__(self, aug_list, skip_prob=0.0):
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if _pyrandom.random() < self.skip_prob or not self.aug_list:
            return src, label
        return _pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Flip image + x-coordinates with probability p
    (ref: detection.py — DetHorizontalFlipAug)."""

    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() < self.p:
            src = _to_np(src)[:, ::-1, :]
            label = label.copy()
            valid = label[:, 0] >= 0
            x1 = label[valid, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - x1
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop with a minimum-object-coverage constraint
    (ref: detection.py — DetRandomCropAug): sample crops until one keeps
    every surviving object covered by >= min_object_covered; boxes are
    clipped and re-normalized to the crop."""

    def __init__(self, min_object_covered=0.3, aspect_ratio_range=(0.75,
                 1.33), area_range=(0.3, 1.0), max_attempts=25):
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def _try_crop(self, h, w):
        area = h * w
        for _ in range(self.max_attempts):
            target_area = _pyrandom.uniform(*self.area_range) * area
            ratio = _pyrandom.uniform(*self.aspect_ratio_range)
            cw = int(round(np.sqrt(target_area * ratio)))
            ch = int(round(np.sqrt(target_area / ratio)))
            if cw <= w and ch <= h:
                x0 = _pyrandom.randint(0, w - cw)
                y0 = _pyrandom.randint(0, h - ch)
                return x0, y0, cw, ch
        return None

    def __call__(self, src, label):
        img = _to_np(src)
        h, w = img.shape[:2]
        crop = self._try_crop(h, w)
        if crop is None:
            return img, label
        x0, y0, cw, ch = crop
        # crop window in normalized coords
        nx0, ny0 = x0 / w, y0 / h
        nx1, ny1 = (x0 + cw) / w, (y0 + ch) / h
        out = label.copy()
        valid = out[:, 0] >= 0
        boxes = out[valid, 1:5]
        if len(boxes):
            ix0 = np.maximum(boxes[:, 0], nx0)
            iy0 = np.maximum(boxes[:, 1], ny0)
            ix1 = np.minimum(boxes[:, 2], nx1)
            iy1 = np.minimum(boxes[:, 3], ny1)
            inter = np.maximum(ix1 - ix0, 0) * np.maximum(iy1 - iy0, 0)
            area = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
            cover = np.where(area > 0, inter / np.maximum(area, 1e-12), 0)
            keep = cover >= self.min_object_covered
            if not keep.any():
                return img, label  # crop would drop everything — skip
            # clip + renormalize survivors; drop the rest
            nb = np.stack([
                (np.clip(boxes[:, 0], nx0, nx1) - nx0) / (nx1 - nx0),
                (np.clip(boxes[:, 1], ny0, ny1) - ny0) / (ny1 - ny0),
                (np.clip(boxes[:, 2], nx0, nx1) - nx0) / (nx1 - nx0),
                (np.clip(boxes[:, 3], ny0, ny1) - ny0) / (ny1 - ny0),
            ], axis=1)
            rows = np.where(valid)[0]
            out[rows, 1:5] = nb
            out[rows[~keep], 0] = -1  # invalidate dropped objects
        return img[y0:y0 + ch, x0:x0 + cw], out


class DetRandomPadAug(DetAugmenter):
    """Pad to a random larger canvas, boxes shrink accordingly
    (ref: detection.py — DetRandomPadAug)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=25, pad_val=127):
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        img = _to_np(src)
        h, w = img.shape[:2]
        for _ in range(self.max_attempts):
            scale = _pyrandom.uniform(*self.area_range)
            ratio = _pyrandom.uniform(*self.aspect_ratio_range)
            nw = int(round(np.sqrt(scale * h * w * ratio)))
            nh = int(round(np.sqrt(scale * h * w / ratio)))
            if nw >= w and nh >= h:
                x0 = _pyrandom.randint(0, nw - w)
                y0 = _pyrandom.randint(0, nh - h)
                canvas = np.full((nh, nw, img.shape[2]), self.pad_val,
                                 img.dtype)
                canvas[y0:y0 + h, x0:x0 + w] = img
                out = label.copy()
                valid = out[:, 0] >= 0
                out[valid, 1] = (out[valid, 1] * w + x0) / nw
                out[valid, 3] = (out[valid, 3] * w + x0) / nw
                out[valid, 2] = (out[valid, 2] * h + y0) / nh
                out[valid, 4] = (out[valid, 4] * h + y0) / nh
                return canvas, out
        return img, label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0,
                       min_object_covered=0.3,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.3, 3.0), max_attempts=25,
                       pad_val=(127, 127, 127)):
    """Standard detection augmenter chain (ref: detection.py —
    CreateDetAugmenter). rand_crop/rand_pad are application
    probabilities."""
    auglist = []
    if resize > 0:
        from .image import ResizeAug

        auglist.append(DetBorrowAug(ResizeAug(resize)))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered,
                                aspect_ratio_range,
                                (area_range[0], min(1.0, area_range[1])),
                                max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1.0 - rand_crop))
    if rand_pad > 0:
        padder = DetRandomPadAug(aspect_ratio_range,
                                 (1.0, max(1.0, area_range[1])),
                                 max_attempts, pad_val[0])
        auglist.append(DetRandomSelectAug([padder], 1.0 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    # color/cast augs built directly — CreateAugmenter always appends a
    # CenterCrop to its data_shape, which would destroy the image here
    from .image import (BrightnessJitterAug, CastAug, ColorNormalizeAug,
                        ContrastJitterAug, SaturationJitterAug)

    if brightness:
        auglist.append(DetBorrowAug(BrightnessJitterAug(brightness)))
    if contrast:
        auglist.append(DetBorrowAug(ContrastJitterAug(contrast)))
    if saturation:
        auglist.append(DetBorrowAug(SaturationJitterAug(saturation)))
    auglist.append(DetBorrowAug(CastAug()))
    if mean is not None or std is not None:
        mean = np.asarray(mean if mean is not None else (0, 0, 0),
                          np.float32)
        std = np.asarray(std if std is not None else (1, 1, 1), np.float32)
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator: multi-object labels, padded to a fixed
    max-objects width (ref: detection.py — ImageDetIter). Yields data
    (B, 3, H, W) and label (B, max_objects, label_width)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root=None, imglist=None,
                 aug_list=None, label_width=5, max_objects=16, **kwargs):
        # split base-iterator options from augmenter options
        iter_kwargs = {k: kwargs.pop(k) for k in
                       ("shuffle", "path_imgidx", "data_name", "label_name")
                       if k in kwargs}
        if aug_list is not None and kwargs:
            raise MXNetError(
                "augmenter options %s conflict with an explicit aug_list "
                "— put them in the aug_list instead" % sorted(kwargs))
        super().__init__(batch_size, data_shape, label_width=label_width,
                         path_imgrec=path_imgrec,
                         path_imglist=path_imglist, path_root=path_root,
                         imglist=imglist,
                         aug_list=aug_list if aug_list is not None
                         else CreateDetAugmenter(data_shape, **kwargs),
                         **iter_kwargs)
        self.max_objects = max_objects

    @property
    def provide_label(self):
        return [DataDesc(self._label_name,
                         (self.batch_size, self.max_objects,
                          self.label_width))]

    def _parse_label(self, raw):
        """Flat header label -> (max_objects, label_width), padded with
        class -1 rows. Accepts either bare object rows or the reference's
        [header_width, label_width, ...objects] packed form."""
        flat = np.asarray(raw, np.float32).ravel()
        lw = self.label_width
        if flat.size >= 2 and float(flat[0]).is_integer() and \
                flat.size > 2 and (flat.size - int(flat[0])) % lw == 0 \
                and int(flat[1]) == lw:
            flat = flat[int(flat[0]):]  # strip packed header
        n = flat.size // lw
        objs = flat[:n * lw].reshape(n, lw)
        out = np.full((self.max_objects, lw), -1.0, np.float32)
        out[:min(n, self.max_objects)] = objs[:self.max_objects]
        return out

    def next(self):
        c, h, w = self.data_shape
        data = np.zeros((self.batch_size, h, w, c), np.float32)
        labels = np.full((self.batch_size, self.max_objects,
                          self.label_width), -1.0, np.float32)
        i = 0
        pad = 0
        while i < self.batch_size:
            try:
                label, img_bytes = self.next_sample()
            except StopIteration:
                if i == 0:
                    raise
                pad = self.batch_size - i
                break
            img = imdecode(img_bytes)
            lbl = self._parse_label(label)
            for aug in self.aug_list:
                img, lbl = aug(img, lbl)
            arr = _to_np(img)
            if arr.shape[:2] != (h, w):
                arr = _to_np(imresize(arr, w, h))
            data[i] = arr.astype(np.float32)
            labels[i] = lbl
            i += 1
        batch_data = NDArray(np.transpose(data, (0, 3, 1, 2)))
        return DataBatch(data=[batch_data], label=[NDArray(labels)],
                         pad=pad)
