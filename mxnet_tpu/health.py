"""Training-health plane — on-device per-layer gradient telemetry,
host-side anomaly detection, fleet skew watch, and a declarative rules
engine (the training twin of the serving observability stack).

The reference exposed per-tensor training statistics through
``mx.mon.Monitor`` (a stat_func tapped on every executor output) and
``MXNET_PROFILER``-driven dumps. Both assume an eager engine where every
tensor crosses the host per step. In the one-launch world that design is
exactly the regression class ``tools/check_host_syncs.py`` polices: a
per-step host read of a gradient norm would re-synchronize the async
dispatch window and undo the pipelining (PR 4/PR 7). This module
rebuilds the Monitor's job under the sync budget:

- :func:`stat_row` packs per-layer grad-norm / param-norm /
  update-ratio plus the step loss into ONE small float32 row INSIDE the
  donated step program (XLA fuses the reductions into the step — intra-
  program accumulation is nearly free, arXiv:2301.13062). The step
  builders (gluon/train_step.py, parallel/sharded.py) stage the row
  into their InflightWindow, so K steps of stats ride the SAME single
  deferred read the engine already performs: syncs/step is bit-equal
  with health on vs off (bench ``training_health_ab`` asserts it).
- :class:`HealthMonitor` consumes retired rows host-side (window
  retirement is the one sanctioned materialization point): loss-spike
  (z-score vs a host EMA/variance tracker), grad-explosion/vanish, and
  dead-layer detectors emit typed flight-recorder events,
  ``mxt_health_anomalies_total{kind,layer}``, an optional post-mortem,
  and — with ``MXT_HEALTH_GUARD_HOOK`` — feed the
  ``MXT_SKIP_NONFINITE`` guard's host bookkeeping (never the weights:
  detection is observability, the on-device skip stays the guard's own
  ``lax.cond``).
- per-host gauges (``mxt_health_host_step_ms``,
  ``mxt_health_grad_fingerprint``) publish into the process registry
  the PR 13 FleetCollector already scrapes; :func:`fleet_skew` turns
  the merged per-member view into straggler/divergence verdicts
  (``mxt_health_step_skew_ratio``, slowest-host gauge) the reshard
  controller and autoscaler can consume.
- :class:`HealthRule` / :class:`RuleEngine` evaluate declarative
  threshold / burn-rate / trend rules over the metrics registry
  (training AND serving SLOs); verdicts render as the telemetry
  endpoint's ``/health`` route and mxt_top's ``health`` section.

Host/device split: everything here is host arithmetic over rows the
engine already read, wall clocks, and registry values — the module is
scanned by tools/check_host_syncs.py with the full pattern set, and the
only annotated reads are window-retirement rows that are host data by
construction.
"""
from __future__ import annotations

import json
import math
import threading
import time

import numpy as _np

__all__ = [
    "enabled", "stat_layout", "stat_row", "HealthMonitor",
    "HealthRule", "RuleEngine", "default_engine", "add_rule",
    "evaluate_rules", "install_default_rules", "fleet_skew",
    "render_health", "handle_health", "reset",
]


def _config():
    from . import config

    return config


def _telemetry():
    from . import telemetry

    return telemetry


def _diag():
    from . import diagnostics

    return diagnostics


def enabled():
    """Whether the fused step builders compile the stat row into their
    program — read at build time, like MXT_SKIP_NONFINITE."""
    return bool(_config().get("MXT_HEALTH"))


# ---------------------------------------------------------------------------
# on-device stat packing (called INSIDE the donated step program)
# ---------------------------------------------------------------------------
def stat_layout(layer_names):
    """Column names of one packed stat row, in order: the step loss,
    then a grad-norm / param-norm / update-ratio block per trainable
    layer, then the guard bit (this step's non-finite flag, 0.0 when
    no guard is compiled in)."""
    cols = ["loss"]
    cols += ["grad_norm:%s" % n for n in layer_names]
    cols += ["param_norm:%s" % n for n in layer_names]
    cols += ["update_ratio:%s" % n for n in layer_names]
    cols.append("nonfinite")
    return cols


def stat_row(loss_vec, grads, old_vals, new_vals, mask=None):
    """Pack one step's health stats into a (3L+2,) float32 row — pure
    jnp, traced INSIDE the donated step program (never a host
    transfer): per-layer gradient L2 norm, post-update parameter L2
    norm, and update ratio ``||w_new - w_old|| / (||w_old|| + eps)``
    (a skipped guard step packs ratio 0 — new == old by construction).
    ``mask`` is the guard bitmask whose newest bit is THIS step; only
    that bit is packed (exact in float32, unlike the full shifted
    mask), so guard-mode callers retire flags and stats from the same
    stacked read."""
    import jax.numpy as jnp

    f32 = jnp.float32

    def _norm(a):
        return jnp.linalg.norm(jnp.ravel(a).astype(f32))

    eps = f32(1e-12)
    parts = [jnp.mean(jnp.asarray(loss_vec, f32)).reshape(1)]
    if grads:
        parts.append(jnp.stack([_norm(g) for g in grads]))
        parts.append(jnp.stack([_norm(w) for w in new_vals]))
        parts.append(jnp.stack(
            [_norm(w2 - w1) / (_norm(w1) + eps)
             for w1, w2 in zip(old_vals, new_vals)]))
    if mask is None:
        bit = jnp.zeros((1,), f32)
    else:
        bit = (mask & jnp.uint32(1)).astype(f32).reshape(1)
    parts.append(bit)
    return jnp.concatenate(parts)


def apply_grad_spike(grads, layer_names, scale):
    """Compile the seeded ``grad_spike`` chaos rule into the step
    program: multiply ONE layer's gradient by the traced ``scale``
    scalar (1.0 on every non-firing step — the host passes S only on
    the step the seeded dice selected). Returns the grads unchanged
    when no rule is armed. Called at trace time by the step builders;
    the rule params come from resilience.fault_point()."""
    from . import resilience

    rule = resilience.fault_point().rule("grad_spike")
    if not rule:
        return grads
    idx = int(rule.get("layer", 0))
    idx = max(0, min(idx, len(grads) - 1)) if grads else 0
    out = list(grads)
    if out:
        out[idx] = out[idx] * scale
    return tuple(out)


def grad_spike_scale(dispatch_no):
    """Host-side half of the ``grad_spike`` rule: the gradient scale to
    pass into this dispatch (1.0 = no perturbation). Consults the
    seeded FaultInjector once the dispatch count passes ``after=`` —
    deterministic under MXT_CHAOS_SEED, n-capped like every rule."""
    from . import resilience

    fp = resilience.fault_point()
    rule = fp.rule("grad_spike")
    if not rule:
        return 1.0
    after = int(rule.get("after", 0))
    if dispatch_no <= after:
        return 1.0
    if not fp.should("grad_spike"):
        return 1.0
    return float(rule.get("scale", 1e4))  # sync-ok: host rule param


# ---------------------------------------------------------------------------
# host-side anomaly detection (window retirement)
# ---------------------------------------------------------------------------
class HealthMonitor:
    """Consume retired stat rows and detect anomalies — pure host
    arithmetic on rows the engine's deferred read already materialized.

    One monitor per step builder (train_step / sharded); ``consume``
    runs inside the InflightWindow's ``on_values`` retirement callback,
    in dispatch order, possibly K steps after the launch. Detectors:

    - loss_spike: |loss - EMA| > z * stddev (after an 8-step warmup)
    - grad_explosion: a layer grad norm above MXT_HEALTH_EXPLODE or
      non-finite
    - dead_layer: MXT_HEALTH_DEAD_STEPS consecutive steps with a layer
      grad norm below MXT_HEALTH_VANISH

    Each anomaly emits a typed flight-recorder event
    (``health_anomaly``), bumps ``mxt_health_anomalies_total{kind,
    layer}``, optionally dumps ONE post-mortem per kind
    (MXT_HEALTH_POSTMORTEM), and — when MXT_HEALTH_GUARD_HOOK is on —
    routes grad explosions into the guard's host bookkeeping via
    ``guard_hook`` (numerics untouched: the hook is bookkeeping only).
    """

    _WARMUP = 8  # steps before the loss-spike z-score is trusted

    def __init__(self, layer_names, stream="fused_step", guard_hook=None):
        cfg = _config()
        self.layer_names = list(layer_names)
        self.columns = stat_layout(self.layer_names)
        self.stream = stream
        self._guard_hook = guard_hook
        self._spike_z = float(cfg.get("MXT_HEALTH_SPIKE_Z"))  # sync-ok: host config scalar
        self._explode = float(cfg.get("MXT_HEALTH_EXPLODE"))  # sync-ok: host config scalar
        self._vanish = float(cfg.get("MXT_HEALTH_VANISH"))  # sync-ok: host config scalar
        self._dead_steps = max(1, int(cfg.get("MXT_HEALTH_DEAD_STEPS")))
        self._decay = float(cfg.get("MXT_HEALTH_EMA_DECAY"))  # sync-ok: host config scalar
        self._hook_on = bool(cfg.get("MXT_HEALTH_GUARD_HOOK"))
        self._postmortem = bool(cfg.get("MXT_HEALTH_POSTMORTEM"))
        self._lock = threading.Lock()
        self._ema = None
        self._var = 0.0
        self._seen = 0
        self._vanish_run = [0] * len(self.layer_names)
        self._dumped_kinds = set()
        self._first_wall = None
        self.anomaly_count = 0
        tel = _telemetry()
        self._anom = tel.counter(
            "mxt_health_anomalies_total",
            "Training-health anomalies by detector kind and layer "
            "(health.py — evaluated host-side at window retirement).",
            ("kind", "layer"))
        self._g_ema = tel.gauge(
            "mxt_health_loss_ema",
            "Host-side EMA of the fused step loss (the loss-spike "
            "detector's baseline).")
        self._g_var = tel.gauge(
            "mxt_health_loss_var",
            "Host-side EMA variance of the fused step loss.")
        self._g_gnorm = tel.gauge(
            "mxt_health_grad_norm",
            "Per-layer gradient L2 norm from the last retired stat row "
            "(computed on device inside the fused step).", ("layer",))
        self._g_uratio = tel.gauge(
            "mxt_health_update_ratio",
            "Per-layer ||delta_w|| / ||w|| from the last retired stat "
            "row.", ("layer",))
        self._g_fp = tel.gauge(
            "mxt_health_grad_fingerprint",
            "Global gradient-norm fingerprint (L2 over all layers) — "
            "the fleet skew watch compares it across members to catch "
            "numeric divergence.")
        self._g_step = tel.gauge(
            "mxt_health_host_step_ms",
            "Mean wall-clock ms per retired training step on THIS host "
            "— the fleet skew watch's straggler signal.")

    # -- the retirement callback ------------------------------------------
    def consume(self, step_no, row):
        """Land ONE retired step's stat row into detection + gauges.
        ``row`` is host data (the engine's stacked deferred read
        already materialized it)."""
        row = _np.asarray(row, dtype=_np.float64)  # sync-ok: retired host row
        now = time.perf_counter()
        with self._lock:
            self._seen += 1
            if self._first_wall is None:
                self._first_wall = now
            elif self._seen > 1:
                span = now - self._first_wall
                self._g_step.set(1000.0 * span / (self._seen - 1))
            L = len(self.layer_names)
            loss = float(row[0])  # sync-ok: retired host row scalar
            gnorms = row[1:1 + L]
            uratios = row[1 + 2 * L:1 + 3 * L]
            self._check_loss(loss, step_no)
            fp = 0.0
            for i, name in enumerate(self.layer_names):
                g = float(gnorms[i])  # sync-ok: retired host row scalar
                fp += g * g if math.isfinite(g) else 0.0
                self._g_gnorm.labels(name).set(g)
                self._g_uratio.labels(name).set(
                    float(uratios[i]))  # sync-ok: retired host row scalar
                self._check_layer(name, i, g, step_no)
            self._g_fp.set(math.sqrt(fp))

    def _check_loss(self, loss, step_no):
        if self._ema is None:
            self._ema, self._var = loss, 0.0
            self._g_ema.set(loss)
            return
        sd = math.sqrt(max(self._var, 0.0))
        if not math.isfinite(loss):
            self._anomaly("loss_spike", "loss", step_no, loss)
        elif self._seen > self._WARMUP and sd > 0.0 and \
                abs(loss - self._ema) > self._spike_z * sd:
            self._anomaly("loss_spike", "loss", step_no, loss)
        if math.isfinite(loss):
            d = loss - self._ema
            a = 1.0 - self._decay
            self._ema += a * d
            self._var = self._decay * (self._var + a * d * d)
        self._g_ema.set(self._ema)
        self._g_var.set(self._var)

    def _check_layer(self, name, i, gnorm, step_no):
        if not math.isfinite(gnorm) or gnorm > self._explode:
            self._anomaly("grad_explosion", name, step_no, gnorm)
            if self._hook_on and self._guard_hook is not None:
                # the MXT_SKIP_NONFINITE host bookkeeping path —
                # skipped-step counter + AMP backoff, never the weights
                self._guard_hook()
            self._vanish_run[i] = 0
            return
        if gnorm < self._vanish:
            self._vanish_run[i] += 1
            if self._vanish_run[i] == self._dead_steps:
                self._anomaly("dead_layer", name, step_no, gnorm)
        else:
            self._vanish_run[i] = 0

    def _anomaly(self, kind, layer, step_no, value):
        self.anomaly_count += 1
        self._anom.labels(kind, layer).inc()
        _diag().record_event("health_anomaly", detector=kind,
                             layer=layer, stream=self.stream,
                             step=int(step_no),
                             value=float(value)  # sync-ok: host detector scalar
                             if math.isfinite(value) else repr(value))
        if self._postmortem and kind not in self._dumped_kinds:
            self._dumped_kinds.add(kind)
            try:
                _diag().dump_postmortem(
                    "health_anomaly", extra={
                        "kind": kind, "layer": layer,
                        "step": int(step_no), "stream": self.stream})
            except Exception:  # noqa: BLE001 — diagnostics must not fail a step
                pass


# ---------------------------------------------------------------------------
# declarative rules engine
# ---------------------------------------------------------------------------
def _metric_value(name, labels=None, quantile=None):
    """Current value of a registry metric (sum over children, or the
    one child matching ``labels``); histogram families read as the
    requested quantile. None when the family doesn't exist yet."""
    tel = _telemetry()
    fam = tel.registry().get(name)
    if fam is None:
        return None
    want = None
    if labels is not None:
        want = tuple(str(labels[k]) for k in fam.labelnames)
    if fam.kind == "histogram":
        total = None
        for values, child in fam.children().items():
            if want is not None and values != want:
                continue
            snap = child.snapshot()
            if snap["count"]:
                q = tel.histogram_quantile(
                    quantile if quantile is not None else 0.5,
                    list(snap["buckets"]), list(snap["counts"]))
                total = q if total is None else max(total, q)
        return total
    total, seen = 0.0, False
    for values, child in fam.children().items():
        if want is not None and values != want:
            continue
        total += float(child.value)  # sync-ok: host registry scalar
        seen = True
    return total if seen else None


class HealthRule:
    """One declarative SLO/health rule over the metrics registry.

    ``kind``:

    - ``threshold`` — breach when the metric's CURRENT value compares
      ``op`` against ``value`` (e.g. skew ratio > 1.5).
    - ``burn_rate`` — breach when the metric's per-second rate of
      change since the previous evaluation compares ``op`` against
      ``value`` (counters: anomaly burn, router-drop burn).
    - ``trend`` — breach when the metric's slope (units/second) over
      the last ``window`` seconds of evaluations compares ``op``
      against ``value`` (e.g. loss EMA rising).

    A rule names the BAD condition, alert-style: ``ok`` is False when
    the condition holds, True when it doesn't, None while the metric
    has no data (or a rate/trend has fewer than two points).
    """

    _OPS = {">": lambda a, b: a > b, "<": lambda a, b: a < b,
            ">=": lambda a, b: a >= b, "<=": lambda a, b: a <= b}

    def __init__(self, name, metric, kind="threshold", op=">", value=0.0,
                 labels=None, quantile=None, window=60.0,
                 description=""):
        if kind not in ("threshold", "burn_rate", "trend"):
            from .base import MXNetError

            raise MXNetError(
                "HealthRule kind must be threshold|burn_rate|trend, "
                "got %r" % (kind,))
        if op not in self._OPS:
            from .base import MXNetError

            raise MXNetError("HealthRule op must be one of %s, got %r"
                             % (sorted(self._OPS), op))
        self.name = name
        self.metric = metric
        self.kind = kind
        self.op = op
        self.value = float(value)  # sync-ok: host rule param
        self.labels = dict(labels) if labels else None
        self.quantile = quantile
        self.window = float(window)  # sync-ok: host rule param
        self.description = description
        self._history = []  # (ts, value) of past evaluations

    def evaluate(self, now=None):
        """One verdict dict: {rule, kind, metric, value, ok, detail}."""
        now = time.time() if now is None else now
        cur = _metric_value(self.metric, self.labels, self.quantile)
        verdict = {"rule": self.name, "kind": self.kind,
                   "metric": self.metric, "value": cur, "ok": None,
                   "detail": ""}
        if cur is None:
            verdict["detail"] = "no data"
            return verdict
        if self.kind == "threshold":
            breach = self._OPS[self.op](cur, self.value)
            verdict["ok"] = not breach
            verdict["detail"] = "%.6g %s %.6g" % (cur, self.op,
                                                  self.value)
            return verdict
        self._history.append((now, cur))
        cutoff = now - self.window
        self._history = [(t, v) for t, v in self._history
                         if t >= cutoff][-64:]
        if len(self._history) < 2:
            verdict["detail"] = "warming (1 sample)"
            return verdict
        if self.kind == "burn_rate":
            (t0, v0), (t1, v1) = self._history[-2], self._history[-1]
        else:  # trend: slope over the whole retained window
            (t0, v0), (t1, v1) = self._history[0], self._history[-1]
        dt = t1 - t0
        if dt <= 0:
            verdict["detail"] = "warming (zero interval)"
            return verdict
        rate = (v1 - v0) / dt
        breach = self._OPS[self.op](rate, self.value)
        verdict["value"] = rate
        verdict["ok"] = not breach
        verdict["detail"] = "%.6g/s %s %.6g" % (rate, self.op, self.value)
        return verdict


class RuleEngine:
    """Evaluate a set of :class:`HealthRule` over the process registry
    and publish verdicts as ``mxt_health_rule_ok{rule}`` gauges (1 ok,
    0 breached; rules with no data publish nothing)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rules = {}

    def add(self, rule):
        with self._lock:
            self._rules[rule.name] = rule
        return rule

    def remove(self, name):
        with self._lock:
            self._rules.pop(name, None)

    def rules(self):
        with self._lock:
            return [self._rules[n] for n in sorted(self._rules)]

    def evaluate(self, now=None):
        verdicts = [r.evaluate(now=now) for r in self.rules()]
        g = _telemetry().gauge(
            "mxt_health_rule_ok",
            "Health-rule verdicts (1 = ok, 0 = breached) from the "
            "declarative rules engine (health.py).", ("rule",))
        for v in verdicts:
            if v["ok"] is not None:
                g.labels(v["rule"]).set(1.0 if v["ok"] else 0.0)
        return verdicts


_default_engine = None
_defaults_installed = False
_lock = threading.Lock()


def default_engine():
    """The process-default rules engine (what /health evaluates),
    seeded with the standard training + serving rules on first use."""
    global _default_engine, _defaults_installed
    with _lock:
        if _default_engine is None:
            _default_engine = RuleEngine()
        if not _defaults_installed:
            _defaults_installed = True
            install_default_rules(_default_engine)
    return _default_engine


def add_rule(rule):
    return default_engine().add(rule)


def evaluate_rules(now=None):
    return default_engine().evaluate(now=now)


def install_default_rules(engine):
    """The standing rule set: training health (anomaly burn, loss
    trend, fleet skew, MoE router drops) plus whatever serving SLO
    rules the serving metrics module declares. Rules over metrics that
    don't exist yet evaluate as no-data — installing them is free."""
    cfg = _config()
    engine.add(HealthRule(
        "train_anomaly_burn", "mxt_health_anomalies_total",
        kind="burn_rate", op=">", value=0.0,
        description="any training-health anomaly actively firing"))
    engine.add(HealthRule(
        "loss_rising", "mxt_health_loss_ema", kind="trend", op=">",
        value=0.0, window=120.0,
        description="loss EMA trending up over the last 2 minutes"))
    engine.add(HealthRule(
        "step_skew", "mxt_health_step_skew_ratio", kind="threshold",
        op=">",
        value=float(cfg.get("MXT_HEALTH_SKEW_RATIO")),  # sync-ok: host config scalar
        description="slowest fleet member vs median step time"))
    engine.add(HealthRule(
        "moe_router_drop_burn", "mxt_moe_router_drops_total",
        kind="burn_rate", op=">", value=0.0,
        description="MoE router actively dropping tokens over expert "
                    "capacity"))
    try:
        from .serving import metrics as serving_metrics

        for rule in serving_metrics.health_rules():
            engine.add(rule)
    except Exception:  # noqa: BLE001 — serving stack optional here
        pass


# ---------------------------------------------------------------------------
# fleet skew watch (runs on the collector host over the merged view)
# ---------------------------------------------------------------------------
def fleet_skew(fleet_registry, skew_ratio=None, divergence=None):
    """Straggler/divergence verdicts over the FleetCollector's merged
    registry: per-member ``mxt_health_host_step_ms`` gives the step-
    time skew (slowest / median), per-member
    ``mxt_health_grad_fingerprint`` gives numeric divergence (data-
    parallel replicas should observe near-identical global grad
    norms). Publishes ``mxt_health_step_skew_ratio`` and the slowest-
    host gauges into the LOCAL registry so the autoscaler / reshard
    controller (and mxt_top) can consume them; returns the verdict
    dict. Pure host arithmetic over already-scraped wire values."""
    cfg = _config()
    if skew_ratio is None:
        skew_ratio = float(cfg.get("MXT_HEALTH_SKEW_RATIO"))  # sync-ok: host config scalar
    if divergence is None:
        divergence = float(cfg.get("MXT_HEALTH_DIVERGENCE"))  # sync-ok: host config scalar
    steps = fleet_registry.member_values("mxt_health_host_step_ms")
    prints = fleet_registry.member_values("mxt_health_grad_fingerprint")
    verdict = {"members": sorted(steps), "skew_ratio": None,
               "slowest": None, "stragglers": [], "divergent": [],
               "ok": True}
    tel = _telemetry()
    if steps:
        vals = sorted(steps.values())
        mid = vals[len(vals) // 2] if len(vals) % 2 else \
            0.5 * (vals[len(vals) // 2 - 1] + vals[len(vals) // 2])
        slowest = max(steps, key=steps.get)
        ratio = steps[slowest] / mid if mid > 0 else 1.0
        verdict["skew_ratio"] = ratio
        verdict["slowest"] = slowest
        verdict["stragglers"] = sorted(
            m for m, v in steps.items()
            if mid > 0 and v / mid > skew_ratio)
        tel.gauge(
            "mxt_health_step_skew_ratio",
            "Slowest fleet member's step time over the fleet median "
            "(health.fleet_skew; >MXT_HEALTH_SKEW_RATIO = straggler)."
        ).set(ratio)
        tel.gauge(
            "mxt_health_slowest_host_step_ms",
            "Step time of the slowest fleet member.", ("member",)
        ).labels(slowest).set(steps[slowest])
    if prints:
        vals = sorted(prints.values())
        mid = vals[len(vals) // 2] if len(vals) % 2 else \
            0.5 * (vals[len(vals) // 2 - 1] + vals[len(vals) // 2])
        scale = max(abs(mid), 1e-12)
        verdict["divergent"] = sorted(
            m for m, v in prints.items()
            if abs(v - mid) / scale > divergence)
    verdict["ok"] = not verdict["stragglers"] and \
        not verdict["divergent"]
    tel.gauge(
        "mxt_health_fleet_ok",
        "1 when the fleet skew watch sees no straggler and no "
        "divergent member, else 0.").set(1.0 if verdict["ok"] else 0.0)
    if not verdict["ok"]:
        _diag().record_event(
            "health_fleet_skew", stragglers=verdict["stragglers"],
            divergent=verdict["divergent"],
            skew_ratio=verdict["skew_ratio"])
    return verdict


# ---------------------------------------------------------------------------
# the /health payload
# ---------------------------------------------------------------------------
def _anomaly_counts():
    """[(kind, layer, count)] sorted by count desc, from the registry
    (empty when no monitor ever fired)."""
    fam = _telemetry().registry().get("mxt_health_anomalies_total")
    if fam is None:
        return []
    rows = [(values[0], values[1], float(ch.value))  # sync-ok: host registry scalar
            for values, ch in fam.children().items()]
    return sorted(rows, key=lambda r: -r[2])


def render_health(now=None):
    """The ``/health`` route payload: rule verdicts, anomaly counts,
    skew + loss gauges, and an overall status (``ok`` unless any rule
    is breached or any anomaly has fired)."""
    verdicts = evaluate_rules(now=now)
    anomalies = _anomaly_counts()
    breached = [v["rule"] for v in verdicts if v["ok"] is False]
    status = "ok" if not breached and not anomalies else "degraded"
    return {
        "status": status,
        "ts": round(time.time(), 6),
        "rules": verdicts,
        "breached": breached,
        "anomalies": [{"kind": k, "layer": l, "count": c}
                      for k, l, c in anomalies[:10]],
        "loss_ema": _metric_value("mxt_health_loss_ema"),
        "step_skew_ratio": _metric_value("mxt_health_step_skew_ratio"),
    }


def handle_health(now=None):
    """(status_code, content_type, body) for the telemetry endpoint's
    ``/health`` route — 200 when ok, 503 when degraded (the standard
    load-balancer health-check contract)."""
    payload = render_health(now=now)
    code = 200 if payload["status"] == "ok" else 503
    return code, "application/json", json.dumps(payload, indent=2)


def reset():
    """Drop the default engine + installed rules (test isolation)."""
    global _default_engine, _defaults_installed
    with _lock:
        _default_engine = None
        _defaults_installed = False
