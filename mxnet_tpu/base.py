"""Foundation types shared across the package.

TPU-native re-imagining of the reference's ctypes base layer
(ref: python/mxnet/base.py — _LIB/check_call/MXNetError). There is no C API
boundary here: JAX/XLA is the backend, so this module only carries the error
type, dtype tables, and small helpers.
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "MXNetError",
    "numeric_types",
    "integer_types",
    "string_types",
    "DTYPE_NAME_TO_NP",
    "NP_TO_DTYPE_NAME",
    "get_dtype",
    "dtype_name",
]


class MXNetError(RuntimeError):
    """Framework error type (ref: python/mxnet/base.py — MXNetError)."""


numeric_types = (float, int, np.generic)
integer_types = (int, np.integer)
string_types = (str,)

# MXNet dtype flag order (ref: include/mxnet/base.h / mshadow type flags):
# 0: float32, 1: float64, 2: float16, 3: uint8, 4: int32, 5: int8, 6: int64,
# bool and bfloat16 were later additions. We keep the name table and add
# bfloat16 as a first-class citizen since it is the TPU-preferred dtype.
try:  # ml_dtypes ships with jax
    import ml_dtypes as _ml

    _bfloat16 = np.dtype(_ml.bfloat16)
except Exception:  # pragma: no cover
    _bfloat16 = np.dtype("float32")

DTYPE_NAME_TO_NP = {
    "float32": np.dtype(np.float32),
    "float64": np.dtype(np.float64),
    "float16": np.dtype(np.float16),
    "bfloat16": _bfloat16,
    "uint8": np.dtype(np.uint8),
    "int32": np.dtype(np.int32),
    "int8": np.dtype(np.int8),
    "int64": np.dtype(np.int64),
    "bool": np.dtype(np.bool_),
    "int16": np.dtype(np.int16),
    "uint16": np.dtype(np.uint16),
    "uint32": np.dtype(np.uint32),
    "uint64": np.dtype(np.uint64),
}

NP_TO_DTYPE_NAME = {}
for _k, _v in DTYPE_NAME_TO_NP.items():
    # first name wins: if bfloat16 falls back to float32 (no ml_dtypes),
    # float32 must keep its own name
    NP_TO_DTYPE_NAME.setdefault(_v, _k)

# MXNet integer type flags, kept for .params/.ndarray binary format parity
# (ref: src/ndarray/ndarray.cc — NDArray::Save uses mshadow type flags).
DTYPE_NAME_TO_FLAG = {
    "float32": 0,
    "float64": 1,
    "float16": 2,
    "uint8": 3,
    "int32": 4,
    "int8": 5,
    "int64": 6,
    "bool": 7,
    "int16": 8,
    "uint16": 9,
    "uint32": 10,
    "uint64": 11,
    "bfloat16": 12,
}
DTYPE_FLAG_TO_NAME = {v: k for k, v in DTYPE_NAME_TO_FLAG.items()}


def get_dtype(dtype):
    """Normalize a user-provided dtype (name, np.dtype, or type) to np.dtype."""
    if dtype is None:
        return DTYPE_NAME_TO_NP["float32"]
    if isinstance(dtype, str):
        if dtype not in DTYPE_NAME_TO_NP:
            raise MXNetError("unknown dtype %r" % (dtype,))
        return DTYPE_NAME_TO_NP[dtype]
    return np.dtype(dtype)


def dtype_name(dtype) -> str:
    """np.dtype → canonical name string."""
    d = np.dtype(dtype)
    name = NP_TO_DTYPE_NAME.get(d)
    if name is None:
        return d.name
    return name
