"""SymbolBlock — run a Symbol graph as a Gluon block
(ref: python/mxnet/gluon/block.py — SymbolBlock).

The graph evaluates through the registry as one op application, so autograd
records a single vjp over the whole program and gradients flow to the
block's Parameters like any other layer.
"""
from __future__ import annotations

import jax

from ..base import MXNetError
from .. import autograd as ag
from .. import random as _random
from ..ndarray.ndarray import NDArray
from ..ops.registry import Op, apply_op
from ..symbol.symbol import Symbol, Group
from ..symbol.executor import _build_graph_fn
from .block import HybridBlock

__all__ = ["SymbolBlock"]


class SymbolBlock(HybridBlock):
    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        if isinstance(outputs, (list, tuple)):
            outputs = Group(list(outputs))
        if isinstance(inputs, Symbol):
            inputs = [inputs]
        self._sb_symbol = outputs
        self._input_names = [i.name for i in inputs]
        arg_names = outputs.list_arguments()
        aux_names = outputs.list_auxiliary_states()
        self._sb_param_names = [n for n in arg_names
                                if n not in self._input_names]
        self._sb_aux_names = list(aux_names)
        # honor declared var dtypes (sym.var(dtype=...)): a quantized
        # graph's int8 weights must not round-trip through f32 params
        declared_dt = {n.name: n.attrs["__dtype__"]
                       for n in outputs._topo_nodes()
                       if n.is_var() and "__dtype__" in n.attrs}
        for n in self._sb_param_names:
            kw = {"dtype": declared_dt[n]} if n in declared_dt else {}
            p = self.params.get(n, allow_deferred_init=True, **kw)
            self._reg_params[n] = p
        for n in self._sb_aux_names:
            kw = {"dtype": declared_dt[n]} if n in declared_dt else {}
            p = self.params.get(n, grad_req="null",
                                allow_deferred_init=True, **kw)
            self._reg_params[n] = p
        self._eval_cache = {}

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        """Load an exported model (ref: block.py — SymbolBlock.imports)."""
        from .. import symbol as sym_mod
        from ..ndarray import ndarray as _nd

        sym = sym_mod.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [sym_mod.var(n) for n in input_names]
        block = SymbolBlock(sym, inputs)
        if param_file is not None:
            loaded = _nd.load(param_file)
            for k, v in loaded.items():
                name = k.partition(":")[2] if ":" in k else k
                if name in block.params:
                    block.params[name].set_data(v)
        del ctx
        return block

    def _ensure_param_shapes(self, input_arrays):
        need = [n for n in self._sb_param_names + self._sb_aux_names
                if self.params[n]._shape_incomplete()
                or self.params[n]._data is None]
        if not any(self.params[n]._shape_incomplete() for n in need):
            return
        kwargs = {n: a.shape for n, a in zip(self._input_names,
                                             input_arrays)}
        arg_shapes, _, aux_shapes = \
            self._sb_symbol.infer_shape_partial(**kwargs)
        for n, s in zip(self._sb_symbol.list_arguments(), arg_shapes):
            if n in self.params and s is not None \
                    and self.params[n]._shape_incomplete():
                self.params[n].shape = s
        for n, s in zip(self._sb_symbol.list_auxiliary_states(),
                        aux_shapes):
            if n in self.params and s is not None \
                    and self.params[n]._shape_incomplete():
                self.params[n].shape = s

    def forward(self, x, *args):
        inputs = [x] + list(args)
        if len(inputs) != len(self._input_names):
            raise MXNetError(
                "SymbolBlock expects %d inputs (%s), got %d"
                % (len(self._input_names), self._input_names, len(inputs)))
        self._ensure_param_shapes(inputs)
        for n in self._sb_param_names + self._sb_aux_names:
            p = self.params[n]
            if p._deferred_init is not None:
                p._finish_deferred_init()

        train = ag.is_training()
        entry = self._eval_cache.get(train)
        if entry is None:
            entry = self._make_op(train)
            self._eval_cache[train] = entry
        op, aux_out_names = entry

        param_nds = [self.params[n].data() for n in self._sb_param_names]
        aux_nds = [self.params[n].data() for n in self._sb_aux_names]
        result = apply_op(op, *(inputs + param_nds + aux_nds))
        if not isinstance(result, tuple):
            result = (result,)
        n_outs = len(self._sb_symbol._outputs)
        outs = list(result[:n_outs])
        aux_vals = result[n_outs:]
        with ag.pause():
            for name, val in zip(aux_out_names, aux_vals):
                self.params[name].data()._set_data(val.data)
        if n_outs == 1:
            return outs[0]
        return outs

    def _make_op(self, train):
        graph_fn = _build_graph_fn(self._sb_symbol, train)
        input_names = list(self._input_names)
        param_names = list(self._sb_param_names)
        aux_names = list(self._sb_aux_names)
        aux_out_names = []
        if train:
            # discover which aux get updates by a cheap shape-eval later;
            # conservatively, all aux are returned and written back
            aux_out_names = list(aux_names)

        def fn(*flat):
            n_in, n_p = len(input_names), len(param_names)
            arg_vals = dict(zip(input_names, flat[:n_in]))
            arg_vals.update(zip(param_names, flat[n_in:n_in + n_p]))
            aux_vals = dict(zip(aux_names, flat[n_in + n_p:]))
            key = _random.new_key()
            outs, new_aux = graph_fn(arg_vals, aux_vals, key)
            extra = tuple(new_aux.get(n, aux_vals[n])
                          for n in aux_out_names)
            return tuple(outs) + extra

        op = Op("symbol_block_%s" % (self._sb_symbol.name or "group"),
                fn, differentiable=True)
        return op, aux_out_names

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError  # forward() is overridden directly
