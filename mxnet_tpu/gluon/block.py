"""Block / HybridBlock — the Gluon model layer
(ref: python/mxnet/gluon/block.py).

TPU-native CachedOp: ``hybridize()`` makes the block's whole forward ONE
jitted XLA program (ref: src/imperative/cached_op.cc — CachedOp::Forward;
the reference traces to an nnvm graph, we trace to a jaxpr). Parameters are
passed as traced inputs so gradients flow to their autograd leaves; aux-state
mutation inside the trace (BatchNorm running stats) is captured by rebind
detection and returned as extra outputs, then written back — replicating the
reference's in-kernel aux mutation without side effects in the trace.
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict

import jax
import numpy as np

from ..base import MXNetError
from .. import autograd as ag
from .. import random as _random
from ..ndarray.ndarray import NDArray
from ..ndarray import ndarray as _nd
from ..ops.registry import Op, apply_op
from .parameter import (
    Parameter, ParameterDict, DeferredInitializationError, param_trace_scope,
)

__all__ = ["Block", "HybridBlock"]


class _NameManager(threading.local):
    def __init__(self):
        super().__init__()
        self.counters = {}

    def get(self, hint):
        n = self.counters.get(hint, 0)
        self.counters[hint] = n + 1
        return "%s%d_" % (hint, n)


_name_manager = _NameManager()


class _BlockScope:
    """Per-block naming scope (ref: block.py — _BlockScope)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                prefix = _name_manager.get(hint)
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            current._counter[hint] = count + 1
            prefix = "%s%d_" % (hint, count)
        if params is None:
            parent = current._block._params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *args):
        if self._block._empty_prefix:
            return
        _BlockScope._current.value = self._old


class _TraceDepth(threading.local):
    def __init__(self):
        super().__init__()
        self.depth = 0


_trace_depth = _TraceDepth()


class Block:
    """Base model-composition unit (ref: gluon/block.py — Block)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = OrderedDict()
        self._reg_params = {}
        self._forward_hooks = []
        self._forward_pre_hooks = []

    def _alias(self):
        return type(self).__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    @property
    def params(self):
        return self._params

    def name_scope(self):
        return self._scope

    def __repr__(self):
        lines = [type(self).__name__ + "("]
        for key, child in self._children.items():
            child_repr = repr(child).replace("\n", "\n  ")
            lines.append("  (%s): %s" % (key, child_repr))
        lines.append(")")
        return "\n".join(lines)

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            reg = self.__dict__.get("_reg_params")
            if reg is not None:
                reg[name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)
        return hook

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)
        return hook

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def collect_params(self, select=None):
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self._params)
        else:
            pat = re.compile(select)
            ret.update({
                name: p for name, p in self._params.items()
                if pat.match(name)
            })
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for p in self._params.values():
            p.cast(dtype)

    # -- structural save/load (ref: block.py — save_parameters uses
    # attribute-path keys, not prefixed names) -----------------------------
    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + key: val for key, val in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def save_parameters(self, filename, deduplicate=False):
        params = self._collect_params_with_prefix()
        payload = {}
        seen = {}
        for key, p in params.items():
            if deduplicate and id(p) in seen:
                continue
            seen[id(p)] = key
            payload[key] = p.data()
        _nd.save(filename, payload)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        del cast_dtype, dtype_source
        loaded = _nd.load(filename)
        params = self._collect_params_with_prefix()
        if not loaded and not params:
            return
        # legacy files may carry full-name keys (ParameterDict.save)
        if loaded and not any(k in params for k in loaded):
            self.collect_params().load(
                filename, ctx, allow_missing, ignore_extra)
            return
        for name, p in params.items():
            if name in loaded:
                p.set_data(loaded[name].as_in_context(
                    ctx if ctx is not None else loaded[name].context))
            elif not allow_missing:
                raise MXNetError(
                    "parameter %s missing in file %s" % (name, filename))
        if not ignore_extra:
            extra = set(loaded) - set(params)
            if extra:
                raise MXNetError(
                    "file %s has parameters not in this block: %s"
                    % (filename, sorted(extra)))

    # ------------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def summary(self, *inputs):
        out = self(*inputs)
        n_params = sum(
            int(np.prod(p.shape)) for p in self.collect_params().values()
            if p.shape is not None
        )
        print("%s: %d parameters, output %s" % (
            self.name, n_params,
            out.shape if hasattr(out, "shape") else type(out)))
        return out


class HybridBlock(Block):
    """Block whose forward can be compiled into one XLA program
    (ref: gluon/block.py — HybridBlock; hybridize() ≈ CachedOp ≈ jax.jit)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._jit_cache = {}
        self._flags = {}
        # flat (sorted, initialized) Parameter list for _call_cached_op;
        # rebuilding it from collect_params() every call walks the whole
        # block tree — real per-step Python overhead on the hot path
        self._cached_flat_params = None

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  **kwargs):
        """Compile subsequent forwards (ref: block.py — hybridize).
        static_alloc/static_shape are accepted for API parity; XLA always
        plans memory statically (buffer donation covers static_alloc)."""
        self._flags = dict(static_alloc=static_alloc,
                           static_shape=static_shape, **kwargs)
        self._active = active
        self._jit_cache = {}
        self._cached_flat_params = None
        for child in self._children.values():
            if isinstance(child, HybridBlock):
                child.hybridize(active, static_alloc=static_alloc,
                                static_shape=static_shape, **kwargs)

    def cast(self, dtype):
        self._jit_cache = {}
        self._cached_flat_params = None
        super().cast(dtype)

    def infer_shape(self, *args):
        """Layers with deferred-shape params override this; composite blocks
        don't need it (children infer for themselves)."""
        raise MXNetError(
            "%s has deferred-init parameters but does not implement "
            "infer_shape; give explicit shapes (e.g. in_units/in_channels) "
            "or implement infer_shape" % (type(self).__name__,))

    # ------------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        from ..symbol.symbol import Symbol

        if args and isinstance(args[0], Symbol):
            return Block.__call__(self, *args, **kwargs)
        if self._active and _trace_depth.depth == 0:
            return self._call_cached_op(*args, **kwargs)
        return super().__call__(*args, **kwargs)

    def forward(self, x, *args, **kwargs):
        from ..symbol.symbol import Symbol

        if isinstance(x, Symbol):
            # symbolic trace (export / SymbolBlock): params become variables
            from .. import symbol as F

            params = {k: p.var() for k, p in self._reg_params.items()}
            return self.hybrid_forward(F, x, *args, **params, **kwargs)
        try:
            params = {k: p.data() for k, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._deferred_infer(x, *args)
            params = {k: p.data() for k, p in self._reg_params.items()}
        from .. import ndarray as F

        return self.hybrid_forward(F, x, *args, **params, **kwargs)

    def _deferred_infer(self, *args):
        self.infer_shape(*args)
        for p in self._reg_params.values():
            if p._deferred_init is not None:
                p._finish_deferred_init()

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # -- CachedOp ------------------------------------------------------
    def _ensure_initialized(self, *args):
        """Finish any deferred inits by one throwaway eager forward in
        predict mode (shape inference happens layer-locally on call)."""
        needs = any(
            p._deferred_init is not None
            for p in self.collect_params().values()
        )
        if not needs:
            return
        with ag.pause(train_mode=False):
            _trace_depth.depth += 1
            try:
                super().__call__(*args)
            finally:
                _trace_depth.depth -= 1

    def _call_cached_op(self, *args, **kwargs):
        if kwargs:
            # keyword inputs fall back to eager (rare; matches CachedOp's
            # positional-only calling convention)
            return super().__call__(*args, **kwargs)
        from ..parallel.sequence import current_sequence_scope

        if current_sequence_scope() is not None:
            # a single-device whole-block jit cannot host the scope's
            # multi-device shard_map; run op-by-op eager instead — the
            # ring attention itself is still one compiled program, and
            # a stale non-ring trace is never reused inside the scope
            return super().__call__(*args, **kwargs)
        self._ensure_initialized(*args)
        param_objs = self._cached_flat_params
        if param_objs is None:
            # built once after deferred init resolves; invalidated by
            # hybridize()/cast() (structural changes require re-hybridize,
            # matching CachedOp). Buffers are NOT cached — p.data() below
            # stays live across set_data/force_reinit rebinds.
            param_objs = [
                p for _, p in sorted(self.collect_params().items())
                if p._data is not None
            ]
            self._cached_flat_params = param_objs
        param_nds = [p.data() for p in param_objs]
        train = ag.is_training()
        entry = self._jit_cache.get(train)
        if entry is None:
            entry = self._build_cached(train, param_objs)
            self._jit_cache[train] = entry
        jfn, meta, op = entry

        key = _random.new_key()
        flat_inputs = list(args) + param_nds + [key]
        result = apply_op(op, *flat_inputs)
        if not isinstance(result, tuple):
            result = (result,)
        n_outs = meta["n_outs"]
        outs = result[:n_outs]
        aux_vals = result[n_outs:]
        with ag.pause():
            for idx, val in zip(meta["aux_idx"], aux_vals):
                param_objs[idx]._data._set_data(val.data)
        if n_outs == 1:
            return outs[0]
        return list(outs)

    def _build_cached(self, train, param_objs):
        meta = {"n_outs": None, "aux_idx": None}
        block = self

        def raw_fn(*flat):
            n_params = len(param_objs)
            input_datas = flat[: len(flat) - n_params - 1]
            param_datas = flat[len(flat) - n_params - 1: -1]
            key = flat[-1]
            wrappers = [NDArray(d) for d in param_datas]
            mapping = dict(zip(param_objs, wrappers))
            _trace_depth.depth += 1
            try:
                with ag.pause(train_mode=train), _random.key_scope(key), \
                        param_trace_scope(mapping):
                    ins = [NDArray(d) for d in input_datas]
                    out = Block.__call__(block, *ins)
            finally:
                _trace_depth.depth -= 1
            outs = list(out) if isinstance(out, (list, tuple)) else [out]
            out_datas = [o.data for o in outs]
            aux_idx = []
            aux_datas = []
            for i, (w, d0) in enumerate(zip(wrappers, param_datas)):
                if w._data is not d0:  # aux state rebound during trace
                    aux_idx.append(i)
                    aux_datas.append(jax.lax.stop_gradient(w._data))
            meta["n_outs"] = len(out_datas)
            meta["aux_idx"] = aux_idx
            return tuple(out_datas) + tuple(aux_datas)

        jfn = jax.jit(raw_fn)
        op = Op("cached_op_%s" % self.name, jfn, differentiable=True)
        return jfn, meta, op

    # -- symbolic export (P6 wires this to Symbol/JSON) ----------------
    def export(self, path, epoch=0):
        from ..symbol.export import export_block

        return export_block(self, path, epoch)
