"""Gluon — the imperative/hybrid model API
(ref: python/mxnet/gluon/__init__.py)."""
from .parameter import Parameter, Constant, ParameterDict
from .block import Block, HybridBlock
from .symbol_block import SymbolBlock
from .trainer import Trainer
from .train_step import CachedTrainStep, train_step
from . import nn
from . import rnn
from . import loss
from . import utils
from . import data
from . import model_zoo
from . import contrib
from .utils import split_and_load, split_data

__all__ = ["Parameter", "Constant", "ParameterDict", "Block", "HybridBlock",
           "SymbolBlock", "Trainer", "CachedTrainStep", "train_step", "nn",
           "rnn", "loss", "utils", "split_and_load", "split_data"]
