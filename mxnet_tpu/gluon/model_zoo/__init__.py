"""Model zoo (ref: python/mxnet/gluon/model_zoo/__init__.py)."""
from . import vision
from .vision import get_model
from . import bert
from .bert import (
    BERTModel, BERTEncoder, get_bert_model, bert_12_768_12, bert_6_512_8,
    bert_3_64_2,
)
from . import wide_deep as wide_deep_mod
from .wide_deep import WideDeep, wide_deep
from . import gpt
from .gpt import GPTModel, gpt_mini, gpt_small

__all__ = ["vision", "get_model", "bert", "BERTModel", "BERTEncoder",
           "get_bert_model", "bert_12_768_12", "bert_6_512_8",
           "bert_3_64_2", "WideDeep", "wide_deep",
           "gpt", "GPTModel", "gpt_mini", "gpt_small"]
