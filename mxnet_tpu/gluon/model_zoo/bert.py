"""BERT model family (GluonNLP-equivalent; the reference ecosystem ships
BERT in the separate gluon-nlp repo built on these same mxnet primitives —
bert_12_768_12 config. SURVEY §7 P8).

TPU-native choices: multi-head attention runs through the fused Pallas
flash-attention op (ops/attention.py) instead of batch_dot+softmax, the
whole encoder hybridizes into one XLA program, and shapes are static —
padding is handled by an additive attention bias from valid_length.
"""
from __future__ import annotations

import math

from ...base import MXNetError
from ..block import HybridBlock
from .. import nn

__all__ = ["tensor_parallel_rules",
           "BERTEncoder", "BERTModel", "get_bert_model", "bert_12_768_12",
           "bert_6_512_8", "bert_3_64_2"]


class BERTSelfAttention(HybridBlock):
    """Fused-QKV multi-head self-attention over flash_attention.
    ``causal=True`` turns it into decoder-style masked attention (used
    by the GPT zoo model)."""

    def __init__(self, units, num_heads, dropout=0.0, causal=False,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if units % num_heads != 0:
            raise MXNetError("units %d not divisible by num_heads %d"
                             % (units, num_heads))
        self._units = units
        self._num_heads = num_heads
        self._causal = causal
        with self.name_scope():
            self.qkv = nn.Dense(3 * units, flatten=False, in_units=units,
                                prefix="qkv_")
            self.proj = nn.Dense(units, flatten=False, in_units=units,
                                 prefix="proj_")
            self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x, bias=None):
        H = self._num_heads
        D = self._units // H
        qkv = self.qkv(x)  # (B, T, 3C)
        # shape-free (0 copies the input dim): stays traceable as a Symbol
        qkv = F.reshape(qkv, shape=(0, 0, 3, H, D))
        q, k, v = F.split(qkv, num_outputs=3, axis=2, squeeze_axis=True)
        q = F.transpose(q, axes=(0, 2, 1, 3))  # (B, H, T, D)
        k = F.transpose(k, axes=(0, 2, 1, 3))
        v = F.transpose(v, axes=(0, 2, 1, 3))
        out = F.flash_attention(q, k, v, bias, causal=self._causal,
                                sm_scale=1.0 / math.sqrt(D))
        out = F.transpose(out, axes=(0, 2, 1, 3))  # (B, T, H, D)
        out = F.reshape(out, shape=(0, 0, -1))
        out = self.proj(out)
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class BERTPositionwiseFFN(HybridBlock):
    def __init__(self, units, hidden_size, dropout=0.0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.ffn_1 = nn.Dense(hidden_size, flatten=False, in_units=units,
                                  prefix="ffn1_")
            self.ffn_2 = nn.Dense(units, flatten=False, in_units=hidden_size,
                                  prefix="ffn2_")
            self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        out = F.gelu(self.ffn_1(x))
        out = self.ffn_2(out)
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class BERTEncoderCell(HybridBlock):
    """Post-LN transformer layer, BERT-style."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 layer_norm_eps=1e-12, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.attention = BERTSelfAttention(units, num_heads, dropout,
                                               prefix="attn_")
            self.ffn = BERTPositionwiseFFN(units, hidden_size, dropout,
                                           prefix="ffn_")
            self.layer_norm_1 = nn.LayerNorm(epsilon=layer_norm_eps,
                                             in_channels=units,
                                             prefix="ln1_")
            self.layer_norm_2 = nn.LayerNorm(epsilon=layer_norm_eps,
                                             in_channels=units,
                                             prefix="ln2_")

    def hybrid_forward(self, F, x, bias=None):
        out = self.layer_norm_1(x + self.attention(x, bias))
        out = self.layer_norm_2(out + self.ffn(out))
        return out


class BERTEncoder(HybridBlock):
    """Stack of encoder cells (GluonNLP BERTEncoder equivalent)."""

    def __init__(self, num_layers, units, hidden_size, num_heads,
                 dropout=0.0, layer_norm_eps=1e-12, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_layers = num_layers
        with self.name_scope():
            self.cells = []
            for i in range(num_layers):
                cell = BERTEncoderCell(units, hidden_size, num_heads,
                                       dropout, layer_norm_eps,
                                       prefix="layer%d_" % i)
                self.register_child(cell, "layer%d" % i)
                self.cells.append(cell)

    def hybrid_forward(self, F, x, bias=None):
        for cell in self.cells:
            x = cell(x, bias)
        return x


class BERTModel(HybridBlock):
    """BERT with MLM + NSP heads (GluonNLP BERTModel equivalent).

    forward(inputs, token_types, valid_length=None) →
        (sequence_output (B,T,C), pooled_output (B,C))
    Use ``decode_mlm(sequence_output)`` for vocabulary scores and
    ``classify_nsp(pooled)`` for next-sentence logits.
    """

    def __init__(self, num_layers=12, units=768, hidden_size=3072,
                 num_heads=12, vocab_size=30522, token_type_vocab_size=2,
                 max_length=512, dropout=0.1, layer_norm_eps=1e-12,
                 use_decoder=True, use_classifier=True, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._max_length = max_length
        self._vocab_size = vocab_size
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, units,
                                           prefix="word_embed_")
            self.token_type_embed = nn.Embedding(token_type_vocab_size,
                                                 units,
                                                 prefix="token_type_embed_")
            self.position_weight = self.params.get(
                "position_weight", shape=(max_length, units),
                init="normal")
            self.embed_layer_norm = nn.LayerNorm(epsilon=layer_norm_eps,
                                                 in_channels=units,
                                                 prefix="embed_ln_")
            self.embed_dropout = nn.Dropout(dropout) if dropout else None
            self.encoder = BERTEncoder(num_layers, units, hidden_size,
                                       num_heads, dropout, layer_norm_eps,
                                       prefix="encoder_")
            self.pooler = nn.Dense(units, activation="tanh",
                                   flatten=False, in_units=units,
                                   prefix="pooler_")
            if use_decoder:
                self.mlm_dense = nn.Dense(units, flatten=False,
                                          in_units=units, prefix="mlm_d_")
                self.mlm_ln = nn.LayerNorm(epsilon=layer_norm_eps,
                                           in_channels=units,
                                           prefix="mlm_ln_")
                # decoder ties its weight to word_embed (same (V, units)
                # param), like GluonNLP's BERTModel
                self.mlm_decoder = nn.Dense(vocab_size, flatten=False,
                                            in_units=units,
                                            prefix="mlm_out_",
                                            params=self.word_embed.params)
            else:
                self.mlm_dense = None
            if use_classifier:
                self.nsp_classifier = nn.Dense(2, flatten=False,
                                               in_units=units,
                                               prefix="nsp_")
            else:
                self.nsp_classifier = None

    def hybrid_forward(self, F, inputs, token_types, valid_length=None,
                       position_weight=None):
        if hasattr(inputs, "shape"):  # eager; Symbol trace skips the check
            T = inputs.shape[1]
            if T > self._max_length:
                raise MXNetError("sequence length %d exceeds max_length %d"
                                 % (T, self._max_length))
        x = self.word_embed(inputs) + self.token_type_embed(token_types)
        # slice the learned position table to seq length without reading
        # .shape (keeps the Symbol trace path working)
        pos = F.slice_like(position_weight, F.transpose(inputs), axes=(0,))
        x = x + F.expand_dims(pos, axis=0)
        x = self.embed_layer_norm(x)
        if self.embed_dropout is not None:
            x = self.embed_dropout(x)
        bias = None
        if valid_length is not None:
            bias = F.attention_padding_bias(
                valid_length, max_len=self._max_length)
            bias = F.slice_like(
                F.transpose(bias, axes=(3, 1, 2, 0)),
                F.transpose(inputs), axes=(0,))
            bias = F.transpose(bias, axes=(3, 1, 2, 0))
        seq = self.encoder(x, bias)
        pooled = self.pooler(F.squeeze(
            F.slice(seq, begin=(None, 0, None), end=(None, 1, None)),
            axis=1))
        return seq, pooled

    def decode_mlm(self, sequence_output):
        from ...symbol.symbol import Symbol

        if isinstance(sequence_output, Symbol):
            from ... import symbol as F
        else:
            from ... import ndarray as F

        if self.mlm_dense is None:
            raise MXNetError("model built with use_decoder=False")
        h = self.mlm_ln(F.gelu(self.mlm_dense(sequence_output)))
        return self.mlm_decoder(h)

    def classify_nsp(self, pooled):
        if self.nsp_classifier is None:
            raise MXNetError("model built with use_classifier=False")
        return self.nsp_classifier(pooled)


def get_bert_model(num_layers, units, num_heads, hidden_size=None,
                   vocab_size=30522, max_length=512, dropout=0.1, **kwargs):
    if hidden_size is None:
        hidden_size = 4 * units
    return BERTModel(num_layers=num_layers, units=units,
                     hidden_size=hidden_size, num_heads=num_heads,
                     vocab_size=vocab_size, max_length=max_length,
                     dropout=dropout, **kwargs)


def bert_12_768_12(**kwargs):
    """BERT-base (L=12, H=768, A=12)."""
    return get_bert_model(12, 768, 12, **kwargs)


def bert_6_512_8(**kwargs):
    """Half-depth BERT for medium budgets."""
    return get_bert_model(6, 512, 8, **kwargs)


def bert_3_64_2(**kwargs):
    """Tiny config for tests."""
    kwargs.setdefault("vocab_size", 1000)
    kwargs.setdefault("max_length", 64)
    return get_bert_model(3, 64, 2, **kwargs)


def tensor_parallel_rules():
    """Megatron-style tensor-parallel PartitionSpecs for every BERT size
    (pass to ShardedTrainStep(..., rules=...) with a ("data", "model")
    mesh). Fused QKV and FFN-in are column-parallel (output dim sharded),
    attention proj and FFN-out are row-parallel (input dim sharded) —
    GSPMD then inserts the canonical all-reduce pair per block over the
    "model" axis. Embeddings and LayerNorms stay replicated (the MLM
    decoder ties the word embedding, so sharding it would all-gather
    every step)."""
    from jax.sharding import PartitionSpec as P

    from ... import parallel

    # suffix-anchored so they cover both BERT's ffn_ffn1_* and the GPT
    # zoo model's ffn1_* parameter names (gpt.tensor_parallel_rules
    # delegates here — one rule set to maintain)
    return parallel.sharding_rule(
        (r"attn_qkv_weight$", P("model", None)),
        (r"attn_qkv_bias$", P("model")),
        (r"attn_proj_weight$", P(None, "model")),
        (r"ffn1_weight$", P("model", None)),
        (r"ffn1_bias$", P("model")),
        (r"ffn2_weight$", P(None, "model")),
    )
