"""Decoder-only causal language model (GPT-style).

No analog in the reference tree (its era predates decoder-only LMs as a
zoo staple); included because long-context causal attention is a
first-class target of this build: the attention runs the Pallas flash
kernel with causal masking (ops/attention.py), scales past VMEM via the
chunked-scan path, and shards over long sequences with
parallel.ring_attention (causal ring schedule) — see
tests/test_parallel.py for the sp path.

Pre-LN transformer: ln -> attn -> residual, ln -> mlp -> residual, final
ln, tied output head.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock
from .. import nn
from .bert import BERTSelfAttention

__all__ = ["GPTModel", "gpt_mini", "gpt_small", "tensor_parallel_rules"]


class GPTBlock(HybridBlock):
    """Pre-LN decoder block."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 layer_norm_eps=1e-5, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.ln1 = nn.LayerNorm(epsilon=layer_norm_eps,
                                    in_channels=units, prefix="ln1_")
            self.attn = BERTSelfAttention(units, num_heads, dropout,
                                          causal=True, prefix="attn_")
            self.ln2 = nn.LayerNorm(epsilon=layer_norm_eps,
                                    in_channels=units, prefix="ln2_")
            self.ffn1 = nn.Dense(hidden_size, flatten=False,
                                 in_units=units, prefix="ffn1_")
            self.ffn2 = nn.Dense(units, flatten=False,
                                 in_units=hidden_size, prefix="ffn2_")
            self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        x = x + self.attn(self.ln1(x))
        h = self.ffn2(F.gelu(self.ffn1(self.ln2(x))))
        if self.dropout is not None:
            h = self.dropout(h)
        return x + h


class GPTModel(HybridBlock):
    """Causal LM: token ids (B, T) -> logits (B, T, vocab); the output
    head ties the token embedding."""

    def __init__(self, num_layers=12, units=768, num_heads=12,
                 hidden_size=None, vocab_size=50257, max_length=1024,
                 dropout=0.1, layer_norm_eps=1e-5, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        hidden_size = hidden_size or 4 * units
        self._units = units
        self._max_length = max_length
        self._vocab_size = vocab_size
        with self.name_scope():
            self.tok_embed = nn.Embedding(vocab_size, units,
                                          prefix="tok_embed_")
            self.pos_weight = self.params.get(
                "pos_weight", shape=(max_length, units), init="normal")
            self.embed_dropout = nn.Dropout(dropout) if dropout else None
            self.blocks = []
            for i in range(num_layers):
                blk = GPTBlock(units, hidden_size, num_heads, dropout,
                               layer_norm_eps, prefix="layer%d_" % i)
                self.register_child(blk, "layer%d" % i)
                self.blocks.append(blk)
            self.ln_f = nn.LayerNorm(epsilon=layer_norm_eps,
                                     in_channels=units, prefix="ln_f_")
            # tied output head: shares the (V, units) weight with
            # tok_embed via a shared ParameterDict (same pattern as
            # BERTModel's mlm_decoder)
            self.head = nn.Dense(vocab_size, flatten=False,
                                 in_units=units, use_bias=False,
                                 prefix="head_",
                                 params=self.tok_embed.params)

    def hybrid_forward(self, F, x, pos_weight=None):
        if hasattr(x, "shape"):  # eager; Symbol trace skips the check
            if x.shape[1] > self._max_length:
                raise MXNetError("sequence length %d exceeds max_length %d"
                                 % (x.shape[1], self._max_length))
        h = self.tok_embed(x)
        # slice the learned position table to seq length without reading
        # .shape (keeps the Symbol trace path working)
        pos = F.slice_like(pos_weight, F.transpose(x), axes=(0,))
        h = h + F.expand_dims(pos, axis=0)
        if self.embed_dropout is not None:
            h = self.embed_dropout(h)
        for blk in self.blocks:
            h = blk(h)
        h = self.ln_f(h)
        return self.head(h)


def gpt_mini(**kwargs):
    """4x128x4 toy config for tests/examples."""
    kwargs.setdefault("vocab_size", 1000)
    kwargs.setdefault("max_length", 256)
    return GPTModel(num_layers=4, units=128, num_heads=4, **kwargs)


def gpt_small(**kwargs):
    """GPT-2 small shape (124M)."""
    return GPTModel(num_layers=12, units=768, num_heads=12, **kwargs)


def tensor_parallel_rules():
    """Megatron column/row PartitionSpecs — the suffix-anchored patterns
    in bert.tensor_parallel_rules match this model's parameter names too
    (attn_qkv_*/attn_proj_*/ffn1_*/ffn2_*), so there is exactly one rule
    set to maintain."""
    from .bert import tensor_parallel_rules as _bert_rules

    return _bert_rules()
