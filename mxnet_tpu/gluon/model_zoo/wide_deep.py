"""Wide & Deep (BASELINE config 5; models the reference's
example/sparse/wide_deep — wide = sparse linear over one-hot features,
deep = embeddings + MLP; the sparse side exercises row_sparse Embedding
gradients, sparse optimizer updates, and KVStore row-sparse pull).

TPU-native notes: inside the jitted step both towers are dense XLA
gathers/scatters (static shapes); sparsity pays at the framework boundary
— see mxnet_tpu/sparse.py's design note.
"""
from __future__ import annotations

from .. import nn
from ..block import HybridBlock

__all__ = ["WideDeep", "wide_deep"]


class WideDeep(HybridBlock):
    """Two-tower CTR model.

    Inputs: ``wide_x`` (B, num_wide) int feature ids into one shared wide
    vocabulary; ``deep_x`` (B, num_deep) int ids into the deep vocabulary.
    Output: (B, classes) scores = wide linear score + deep MLP score.
    """

    def __init__(self, wide_vocab, deep_vocab, embed_dim=16,
                 hidden=(64, 32), classes=2, sparse_grad=True,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            # wide tower: Embedding(output_dim=classes) == per-feature
            # weight rows of a sparse linear layer; summing over the
            # feature axis gives w . x for the one-hot encoding
            self.wide = nn.Embedding(wide_vocab, classes,
                                     sparse_grad=sparse_grad,
                                     prefix="wide_")
            self.deep_embedding = nn.Embedding(deep_vocab, embed_dim,
                                               sparse_grad=sparse_grad,
                                               prefix="deep_embed_")
            self.deep = nn.HybridSequential(prefix="deep_")
            with self.deep.name_scope():
                for h in hidden:
                    self.deep.add(nn.Dense(h, activation="relu"))
                self.deep.add(nn.Dense(classes))

    def hybrid_forward(self, F, wide_x, deep_x):
        wide_score = self.wide(wide_x).sum(axis=1)        # (B, classes)
        emb = self.deep_embedding(deep_x)                 # (B, nd, D)
        flat = emb.reshape((emb.shape[0], -1))
        deep_score = self.deep(flat)                      # (B, classes)
        return wide_score + deep_score


def wide_deep(wide_vocab=100000, deep_vocab=10000, **kwargs):
    """Factory matching the get_model convention."""
    return WideDeep(wide_vocab, deep_vocab, **kwargs)
