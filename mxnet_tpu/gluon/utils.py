"""Gluon utilities (ref: python/mxnet/gluon/utils.py)."""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .. import ndarray as _nd

__all__ = ["split_data", "split_and_load", "clip_global_norm"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Slice a batch along batch_axis into num_slice chunks
    (ref: utils.py — split_data)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            "batch size %d cannot be evenly split into %d slices; pad the "
            "batch or set even_split=False" % (size, num_slice))
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(begin, end)
        slices.append(data[tuple(idx)])
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split a batch and load each slice onto one context
    (ref: utils.py — split_and_load). On TPU prefer the sharded data path
    (parallel.shard_batch) which keeps the batch as one sharded array."""
    if not isinstance(data, NDArray):
        data = _nd.array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale NDArrays so the joint L2 norm <= max_norm
    (ref: utils.py — clip_global_norm)."""
    if not arrays:
        raise ValueError("arrays must not be empty")
    total = _nd.sum(arrays[0] * arrays[0])
    for a in arrays[1:]:
        total = total + _nd.sum(a * a)
    total_norm = float(_nd.sqrt(total).asnumpy())
    if check_isfinite and not np.isfinite(total_norm):
        import warnings

        warnings.warn("nan or inf detected in gradients' global norm")
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a *= scale
    return total_norm


def check_sha1(filename, sha1_hash):
    """True if the file's sha1 matches (ref: gluon/utils.py —
    check_sha1; used to validate downloaded model files)."""
    import hashlib

    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            sha1.update(chunk)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):
    """Fetch a URL to a local file (ref: gluon/utils.py — download).
    Same signature/return contract; in a no-egress environment the
    urllib call raises and the error says so plainly. Failed attempts
    back off exponentially (0.5 s, 1 s, 2 s, ... capped at 8 s) instead
    of hammering the server in a tight loop."""
    import os
    import time
    import urllib.request

    if path is None:
        fname = url.split("/")[-1]
    elif os.path.isdir(path):
        fname = os.path.join(path, url.split("/")[-1])
    else:
        fname = path
    if not overwrite and os.path.exists(fname) and (
            sha1_hash is None or check_sha1(fname, sha1_hash)):
        return fname
    d = os.path.dirname(os.path.abspath(fname))
    if d:
        os.makedirs(d, exist_ok=True)
    ctx = None
    if not verify_ssl:
        import ssl

        ctx = ssl._create_unverified_context()
    last = None
    for attempt in range(max(1, retries)):
        if attempt:
            time.sleep(min(0.5 * (2 ** (attempt - 1)), 8.0))
        try:
            with urllib.request.urlopen(url, context=ctx) as r, \
                    open(fname, "wb") as f:
                f.write(r.read())
            if sha1_hash and not check_sha1(fname, sha1_hash):
                raise OSError("sha1 mismatch for %s" % fname)
            return fname
        except Exception as e:  # noqa: BLE001 — retry loop
            last = e
    raise OSError(
        "download of %s failed after %d tries (no network egress in "
        "this environment?): %r" % (url, retries, last))
