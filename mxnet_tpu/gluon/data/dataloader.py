"""DataLoader (ref: python/mxnet/gluon/data/dataloader.py).

The reference forks worker processes that return CPUShared-storage
NDArrays. TPU-native redesign: workers are *threads* by default —
batchification is numpy (releases the GIL in C loops) and the expensive
device transfer happens once on the main thread via a single device_put,
overlapping with compute thanks to XLA async dispatch.

``thread_pool=False`` (with ``num_workers>0``) restores the reference's
process-worker escape hatch for GIL-heavy pure-Python transform chains
(ref: dataloader.py — _MultiWorkerIter + worker_loop): forked workers run
``dataset[i]`` + a numpy-only batchify and ship pickled numpy back; the
parent does the single device_put. Worker code must stay numpy/PIL —
JAX is fork-unsafe once its backend is initialized, so the child path
never touches jax (the reference had the same split: cheap CPUShared
numpy in workers, device copy in the consumer).
"""
from __future__ import annotations

import collections
import concurrent.futures
import multiprocessing

import numpy as np

from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ...ndarray import ndarray as _nd
from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (ref: dataloader.py — default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        # ONE stacked device op instead of an asnumpy() host sync per
        # sample per batch (each sync is a full dispatch round-trip)
        import jax.numpy as jnp

        return NDArray(jnp.stack([d.data for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    out = np.asarray(data)
    return _nd.array(out, dtype=out.dtype)


def _issue_device_put(batch):
    """Issue (async) device placement for every array in a batch. XLA
    dispatch returns immediately, so by the time the consumer's train step
    touches the batch the H2D transfer has been overlapping compute."""
    import jax

    if isinstance(batch, list):
        return [_issue_device_put(b) for b in batch]
    if isinstance(batch, tuple):
        return tuple(_issue_device_put(b) for b in batch)
    if isinstance(batch, dict):
        return {k: _issue_device_put(v) for k, v in batch.items()}
    if isinstance(batch, NDArray):
        batch._set_data(jax.device_put(batch.data))
    return batch


class _DevicePrefetcher:
    """Double-buffer: keep ``depth`` batches materialized ahead of the
    consumer, issuing each one's ``device_put`` as soon as it is pulled —
    so batch N+1's host→device transfer overlaps the step running on
    batch N. Order-preserving; purely a scheduling wrapper. The buffered
    batches' bytes register in the diagnostics HBM ledger ('prefetch'
    pool — shape metadata, never a device read)."""

    def __init__(self, it, depth=2, to_device=True):
        self._it = iter(it)
        self._depth = max(1, depth)
        self._to_device = to_device
        self._buf = collections.deque()
        self._key = "prefetcher-%x" % id(self)

    @staticmethod
    def _batch_nbytes(batch):
        if isinstance(batch, (list, tuple)):
            return sum(_DevicePrefetcher._batch_nbytes(b) for b in batch)
        if isinstance(batch, dict):
            return sum(_DevicePrefetcher._batch_nbytes(b)
                       for b in batch.values())
        return int(getattr(getattr(batch, "data", batch), "nbytes", 0)
                   or 0)

    def _publish(self):
        from ... import diagnostics

        diagnostics.hbm_set(
            "prefetch", self._key,
            sum(self._batch_nbytes(b) for b in self._buf))

    def _pull(self):
        if self._it is None:
            return
        try:
            batch = next(self._it)
        except StopIteration:
            self._it = None
            return
        if self._to_device:
            batch = _issue_device_put(batch)
        self._buf.append(batch)

    def __iter__(self):
        from ... import diagnostics

        try:
            while len(self._buf) < self._depth and self._it is not None:
                self._pull()
            self._publish()
            while self._buf:
                batch = self._buf.popleft()
                self._pull()  # refill BEFORE yielding: next H2D in flight
                self._publish()
                yield batch
        finally:
            diagnostics.hbm_release("prefetch", self._key)


def _np_batchify(data):
    """Numpy-only batchify for process workers (no jax in a forked
    child). Mirrors default_batchify_fn's structure handling."""
    if isinstance(data[0], tuple):
        return tuple(_np_batchify(i) for i in zip(*data))
    if isinstance(data[0], NDArray):
        # reading a device array would re-enter JAX inside a fork()ed
        # child — likely deadlock. Fail loudly with the fix.
        raise TypeError(
            "dataset returned NDArray samples under thread_pool=False; "
            "process workers must stay numpy/PIL (JAX is fork-unsafe). "
            "Return numpy from __getitem__, or use thread workers.")
    return np.asarray(data)


def _np_to_nd(batch):
    if isinstance(batch, tuple):
        return [_np_to_nd(b) for b in batch]
    return _nd.array(batch, dtype=batch.dtype)


# fork-inherited dataset handle (one per worker process)
_worker_dataset = None


def _worker_init(dataset):
    global _worker_dataset
    _worker_dataset = dataset


def _worker_load(indices):
    samples = [_worker_dataset[i] for i in indices]
    return _np_batchify(samples)


def _worker_samples(indices):
    samples = [_worker_dataset[i] for i in indices]
    for s in samples:
        items = s if isinstance(s, tuple) else (s,)
        if any(isinstance(i, NDArray) for i in items):
            # same fork-safety guard as _np_batchify: pickling a device
            # array re-enters JAX inside the forked child
            raise TypeError(
                "dataset returned NDArray samples under "
                "thread_pool=False; process workers must stay numpy/PIL "
                "(JAX is fork-unsafe). Return numpy from __getitem__, or "
                "use thread workers.")
    return samples


class DataLoader:
    """Load a Dataset in mini-batches (ref: dataloader.py — DataLoader)."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=True, prefetch_to_device=False):
        """prefetch: how many batches to keep in flight ahead of the
        consumer (default 2*num_workers). Honored on the num_workers=0
        path too — the serial loader then pulls ``prefetch`` batches
        ahead through the device prefetcher instead of silently ignoring
        the argument.

        prefetch_to_device: double-buffer device placement — issue the
        next batch's ``device_put`` while the current step runs, so H2D
        transfer overlaps compute (the tf.data prefetch_to_device
        analog)."""
        self._dataset = dataset
        del pin_memory  # device placement is one device_put on TPU
        self._prefetch_to_device = prefetch_to_device

        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                if shuffle:
                    sampler = RandomSampler(len(dataset))
                else:
                    sampler = SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(
                sampler, batch_size, last_batch if last_batch else "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._custom_batchify = batchify_fn is not None
        self._thread_pool = thread_pool
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)

    def __len__(self):
        return len(self._batch_sampler)

    def _load_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def _iter_serial(self):
        for indices in self._batch_sampler:
            yield self._load_batch(indices)

    def __iter__(self):
        if self._num_workers == 0:
            base = self._iter_serial()
            if self._prefetch > 0 or self._prefetch_to_device:
                # honor prefetch without workers: pull ahead on the
                # consumer thread so the next batch's transfers are
                # already dispatched when the current step runs
                base = _DevicePrefetcher(base, self._prefetch or 2,
                                         self._prefetch_to_device)
        else:
            base = self._iter_threads() if self._thread_pool \
                else self._iter_processes()
            if self._prefetch_to_device:
                base = _DevicePrefetcher(base, 2, True)
        return self._instrumented(base)

    @staticmethod
    def _instrumented(base):
        """Clock how long the CONSUMER waits for each batch — the
        'data_wait' phase of the step timeline (telemetry.py). With
        healthy prefetch this is ~0; a feed-bound run shows it eating
        the step budget. Host wall-clock only, no device reads."""
        import time

        from ... import telemetry

        it = iter(base)
        n = 0
        while True:
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                return
            n += 1
            telemetry.record_phase("data_wait",
                                   time.perf_counter() - t0,
                                   stream="dataloader", step=n)
            yield batch

    def _iter_threads(self):
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=self._num_workers) as pool:
            pending = collections.deque()
            it = iter(self._batch_sampler)
            try:
                for _ in range(max(1, self._prefetch)):
                    pending.append(pool.submit(self._load_batch, next(it)))
            except StopIteration:
                it = None
            while pending:
                batch = pending.popleft().result()
                if it is not None:
                    try:
                        pending.append(pool.submit(self._load_batch,
                                                   next(it)))
                    except StopIteration:
                        it = None
                yield batch

    def _iter_processes(self):
        """Reference-style fork workers. dataset[i] + numpy batchify run
        in the child; device placement (and any custom batchify_fn, which
        may build NDArrays) runs in the parent. Child exceptions re-raise
        at .result(); an abruptly dead worker (OOM-kill, SIGKILL) is
        detected by the executor and surfaced as a descriptive
        MXNetError rather than hanging the consumer (which a plain
        multiprocessing.Pool would do: its result queue just never
        delivers)."""
        from concurrent.futures.process import BrokenProcessPool

        ctx = multiprocessing.get_context("fork")
        job = _worker_samples if self._custom_batchify else _worker_load
        with concurrent.futures.ProcessPoolExecutor(
                max_workers=self._num_workers, mp_context=ctx,
                initializer=_worker_init,
                initargs=(self._dataset,)) as pool:
            pending = collections.deque()
            it = iter(self._batch_sampler)
            try:
                for _ in range(max(1, self._prefetch)):
                    pending.append(pool.submit(job, next(it)))
            except StopIteration:
                it = None
            while pending:
                try:
                    raw = pending.popleft().result()
                except BrokenProcessPool as e:
                    raise MXNetError(
                        "DataLoader worker process died unexpectedly "
                        "(killed by the OS — OOM? — or crashed hard). "
                        "Reduce worker memory use or num_workers, or "
                        "switch to thread workers (thread_pool=True)."
                    ) from e
                if it is not None:
                    try:
                        pending.append(pool.submit(job, next(it)))
                    except StopIteration:
                        it = None
                if self._custom_batchify:
                    yield self._batchify_fn(raw)
                else:
                    yield _np_to_nd(raw)
