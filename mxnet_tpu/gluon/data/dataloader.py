"""DataLoader (ref: python/mxnet/gluon/data/dataloader.py).

The reference forks worker processes that return CPUShared-storage
NDArrays. TPU-native redesign: workers are *threads* by default —
batchification is numpy (releases the GIL in C loops) and the expensive
device transfer happens once on the main thread via a single device_put,
overlapping with compute thanks to XLA async dispatch. num_workers>0 uses a
thread pool; a multiprocessing path is intentionally not the default (the
reference needed it for Python-speed augmentation; PIL/numpy release the
GIL).
"""
from __future__ import annotations

import concurrent.futures
import queue
import threading

import numpy as np

from ...ndarray.ndarray import NDArray
from ...ndarray import ndarray as _nd
from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (ref: dataloader.py — default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return _nd.array(np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    out = np.asarray(data)
    return _nd.array(out, dtype=out.dtype)


class DataLoader:
    """Load a Dataset in mini-batches (ref: dataloader.py — DataLoader)."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=True):
        self._dataset = dataset
        del pin_memory  # device placement is one device_put on TPU

        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                if shuffle:
                    sampler = RandomSampler(len(dataset))
                else:
                    sampler = SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(
                sampler, batch_size, last_batch if last_batch else "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)

    def __len__(self):
        return len(self._batch_sampler)

    def _load_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._load_batch(indices)
            return

        with concurrent.futures.ThreadPoolExecutor(
                max_workers=self._num_workers) as pool:
            pending = []
            it = iter(self._batch_sampler)
            try:
                for _ in range(max(1, self._prefetch)):
                    pending.append(pool.submit(self._load_batch, next(it)))
            except StopIteration:
                it = None
            while pending:
                batch = pending.pop(0).result()
                if it is not None:
                    try:
                        pending.append(pool.submit(self._load_batch,
                                                   next(it)))
                    except StopIteration:
                        it = None
                yield batch
