"""Datasets (ref: python/mxnet/gluon/data/dataset.py)."""
from __future__ import annotations

import os

from ...ndarray.ndarray import NDArray
from ...ndarray import ndarray as _nd

__all__ = ["Dataset", "ArrayDataset", "SimpleDataset", "RecordFileDataset"]


class Dataset:
    """Abstract dataset: __getitem__ + __len__ (ref: dataset.py — Dataset)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn):
        return SimpleDataset([self[i] for i in range(len(self))
                              if fn(self[i])])

    def take(self, count):
        return SimpleDataset([self[i] for i in
                              range(min(count, len(self)))])

    def transform(self, fn, lazy=True):
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn, lazy=True):
        return self.transform(_TransformFirstClosure(fn), lazy)


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class _TransformFirstClosure:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x, *args):
        if args:
            return (self._fn(x),) + args
        return self._fn(x)


class ArrayDataset(Dataset):
    """Zip of equal-length arrays/datasets (ref: dataset.py — ArrayDataset)."""

    def __init__(self, *args):
        assert len(args) > 0, "Needs at least 1 arrays"
        self._length = len(args[0])
        self._data = []
        for i, data in enumerate(args):
            assert len(data) == self._length, \
                "All arrays must have the same length; array[0] has length " \
                "%d while array[%d] has %d." % (self._length, i, len(data))
            if isinstance(data, NDArray) and data.ndim == 1:
                data = data.asnumpy()
            self._data.append(data)

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(data[idx] for data in self._data)

    def __len__(self):
        return self._length


class RecordFileDataset(Dataset):
    """Dataset over an indexed RecordIO file
    (ref: dataset.py — RecordFileDataset). Reads go through the native
    C++ engine when available (thread-local readers, no lock contention
    across DataLoader worker threads); otherwise the locked Python
    reader."""

    def __init__(self, filename):
        import threading

        from ...recordio import MXIndexedRecordIO

        self.idx_file = os.path.splitext(filename)[0] + ".idx"
        self.filename = filename
        self._record = MXIndexedRecordIO(self.idx_file, self.filename, "r")
        # DataLoader workers are threads here (the reference forks
        # processes); the seek+read pair on the shared handle must be atomic
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._payload = None
        try:
            from ... import native

            if native.available():
                nat = native.NativeRecordReader(filename)
                offs, lens = nat.scan()
                nat.close()
                # map the .idx key order onto scanned records; a stale
                # sidecar falls back to the locked Python reader
                self._payload = native.select_payload_by_starts(
                    offs, lens,
                    [self._record.idx[k] for k in self._record.keys])
                if self._payload is not None:
                    self._native = native
        except Exception:  # noqa: BLE001 — python fallback
            self._payload = None

    def _native_reader(self):
        r = getattr(self._tls, "reader", None)
        if r is None:
            r = self._native.NativeRecordReader(self.filename)
            self._tls.reader = r
        return r

    def __getitem__(self, idx):
        if self._payload is not None:
            offs, lens = self._payload
            return self._native_reader().read_at(int(offs[idx]),
                                                 int(lens[idx]))
        with self._lock:
            return self._record.read_idx(self._record.keys[idx])

    def __len__(self):
        return len(self._record.keys)
