"""Vision transforms (ref: python/mxnet/gluon/data/vision/transforms.py).

Blocks operating on HWC uint8/float images. Host-side numpy/PIL where the
reference used OpenCV ops; ToTensor/Normalize produce the CHW float arrays
the models consume.
"""
from __future__ import annotations

import numpy as np

from ....base import MXNetError
from ....ndarray.ndarray import NDArray
from ....ndarray import ndarray as _nd
from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomBrightness", "RandomContrast", "RandomSaturation",
           "RandomHue", "RandomColorJitter", "RandomLighting",
           "CropResize", "Rotate", "RandomRotation"]


def _to_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)


class Compose(Sequential):
    """Sequentially compose transforms (ref: transforms.py — Compose)."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x


class Cast(Block):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        if isinstance(x, NDArray):
            return x.astype(self._dtype)
        return _nd.array(np.asarray(x), dtype=self._dtype)


class ToTensor(Block):
    """HWC uint8 [0,255] → CHW float32 [0,1] (ref: transforms.py — ToTensor)."""

    def forward(self, x):
        arr = _to_np(x).astype(np.float32) / 255.0
        if arr.ndim == 3:
            arr = arr.transpose(2, 0, 1)
        elif arr.ndim == 4:
            arr = arr.transpose(0, 3, 1, 2)
        return _nd.array(arr)


class Normalize(Block):
    """(x - mean) / std on CHW float input (ref: transforms.py — Normalize)."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = np.asarray(mean, dtype=np.float32)
        self._std = np.asarray(std, dtype=np.float32)

    def forward(self, x):
        arr = _to_np(x).astype(np.float32)
        mean = self._mean.reshape(-1, 1, 1) if self._mean.ndim else self._mean
        std = self._std.reshape(-1, 1, 1) if self._std.ndim else self._std
        return _nd.array((arr - mean) / std)


def _pil_resize(arr, size, interpolation):
    from PIL import Image

    if isinstance(size, int):
        size = (size, size)
    pil = Image.fromarray(arr.astype(np.uint8))
    return np.asarray(pil.resize(tuple(size), interpolation))


class Resize(Block):
    """Resize to (w, h) or short-edge (ref: transforms.py — Resize)."""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interpolation = interpolation

    def forward(self, x):
        from PIL import Image

        arr = _to_np(x)
        interp = Image.BILINEAR if self._interpolation == 1 else \
            Image.NEAREST
        if isinstance(self._size, int) and self._keep:
            h, w = arr.shape[:2]
            if h < w:
                size = (int(w * self._size / h), self._size)
            else:
                size = (self._size, int(h * self._size / w))
        elif isinstance(self._size, int):
            size = (self._size, self._size)
        else:
            size = tuple(self._size)
        return _nd.array(_pil_resize(arr, size, interp))


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._interpolation = interpolation

    def forward(self, x):
        arr = _to_np(x)
        tw, th = self._size
        h, w = arr.shape[:2]
        if h < th or w < tw:
            from PIL import Image

            arr = _pil_resize(arr, (max(tw, w), max(th, h)), Image.BILINEAR)
            h, w = arr.shape[:2]
        y = (h - th) // 2
        x0 = (w - tw) // 2
        return _nd.array(arr[y:y + th, x0:x0 + tw])


class RandomResizedCrop(Block):
    """Random area+aspect crop then resize
    (ref: transforms.py — RandomResizedCrop)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        from PIL import Image

        arr = _to_np(x)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = np.random.uniform(*self._scale) * area
            log_ratio = (np.log(self._ratio[0]), np.log(self._ratio[1]))
            aspect = np.exp(np.random.uniform(*log_ratio))
            cw = int(round(np.sqrt(target_area * aspect)))
            ch = int(round(np.sqrt(target_area / aspect)))
            if cw <= w and ch <= h:
                y = np.random.randint(0, h - ch + 1)
                x0 = np.random.randint(0, w - cw + 1)
                crop = arr[y:y + ch, x0:x0 + cw]
                return _nd.array(_pil_resize(crop, self._size,
                                             Image.BILINEAR))
        # fallback: center crop
        return CenterCrop(self._size)(_nd.array(arr))


class RandomFlipLeftRight(Block):
    def forward(self, x):
        arr = _to_np(x)
        if np.random.rand() < 0.5:
            arr = arr[:, ::-1]
        return _nd.array(np.ascontiguousarray(arr))


class RandomFlipTopBottom(Block):
    def forward(self, x):
        arr = _to_np(x)
        if np.random.rand() < 0.5:
            arr = arr[::-1]
        return _nd.array(np.ascontiguousarray(arr))


class _RandomScale(Block):
    def __init__(self, jitter):
        super().__init__()
        self._jitter = jitter

    def _factor(self):
        return 1.0 + np.random.uniform(-self._jitter, self._jitter)


class RandomBrightness(_RandomScale):
    def forward(self, x):
        arr = _to_np(x).astype(np.float32)
        return _nd.array(np.clip(arr * self._factor(), 0, 255))


class RandomContrast(_RandomScale):
    def forward(self, x):
        arr = _to_np(x).astype(np.float32)
        mean = arr.mean()
        return _nd.array(np.clip((arr - mean) * self._factor() + mean,
                                 0, 255))


class RandomSaturation(_RandomScale):
    def forward(self, x):
        arr = _to_np(x).astype(np.float32)
        gray = arr.mean(axis=-1, keepdims=True)
        f = self._factor()
        return _nd.array(np.clip(arr * f + gray * (1 - f), 0, 255))


class RandomHue(Block):
    """Hue jitter with a factor from [max(0, 1-hue), 1+hue]
    (ref: transforms.py — RandomHue; backend image_random-inl.h uses the
    same YIQ chroma-rotation formulation, vectorized here in numpy)."""

    _t_yiq = np.array([[0.299, 0.587, 0.114],
                       [0.596, -0.274, -0.321],
                       [0.211, -0.523, 0.311]], dtype=np.float32)
    # exact inverse (the textbook t_rgb is truncated to 3 decimals,
    # which breaks the hue=0 == identity contract at uint8 scale)
    _t_rgb = np.linalg.inv(_t_yiq)

    def __init__(self, hue):
        super().__init__()
        self._hue = hue

    def forward(self, x):
        arr = _to_np(x).astype(np.float32)
        f = np.random.uniform(max(0.0, 1 - self._hue), 1 + self._hue)
        theta = (f - 1.0) * np.pi
        u, w = np.cos(theta), np.sin(theta)
        # RGB -> YIQ, rotate the IQ (chroma) plane by theta, -> RGB
        rot = np.array([[1.0, 0.0, 0.0],
                        [0.0, u, -w],
                        [0.0, w, u]], dtype=np.float32)
        m = self._t_rgb @ rot @ self._t_yiq
        return _nd.array(np.clip(arr @ m.T, 0, 255))


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        order = np.random.permutation(len(self._ts))
        for i in order:
            x = self._ts[i](x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise (ref: transforms.py — RandomLighting)."""

    _eigval = np.array([55.46, 4.794, 1.148], dtype=np.float32)
    _eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.8140],
                        [-0.5836, -0.6948, 0.4203]], dtype=np.float32)

    def __init__(self, alpha_std=0.05):
        super().__init__()
        self._alpha_std = alpha_std

    def forward(self, x):
        arr = _to_np(x).astype(np.float32)
        alpha = np.random.normal(0, self._alpha_std, 3).astype(np.float32)
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return _nd.array(np.clip(arr + rgb, 0, 255))


class CropResize(Block):
    """Fixed crop at (x, y, width, height), optionally resized to
    ``size`` (ref: transforms.py — CropResize)."""

    def __init__(self, x, y, width, height, size=None, interpolation=None):
        super().__init__()
        self._x, self._y = x, y
        self._w, self._h = width, height
        self._size = (size, size) if isinstance(size, int) else size
        self._interpolation = interpolation

    def forward(self, data):
        arr = _to_np(data)
        h, w = arr.shape[:2]
        if (self._x < 0 or self._y < 0 or self._w <= 0 or self._h <= 0
                or self._y + self._h > h or self._x + self._w > w):
            raise MXNetError(
                "crop (%d,%d,%d,%d) exceeds image %dx%d"
                % (self._x, self._y, self._w, self._h, w, h))
        out = arr[self._y:self._y + self._h, self._x:self._x + self._w]
        if self._size is not None:
            from PIL import Image

            interp = Image.NEAREST if self._interpolation == 0 \
                else Image.BILINEAR
            out = _pil_resize(out, self._size, interp)
        return _nd.array(out)


def _rotate_np(arr, deg, zoom_in=False, zoom_out=False):
    """Rotation on the host image (ref: transforms.py — Rotate; the
    reference's backend op rotates the tensor; augmentation stays
    host-side here, like the rest of this module). zoom_in crops so no
    padding shows; zoom_out shrinks so the whole rotated frame fits.
    Mid-pipeline float images (color jitter outputs) are handled by the
    uint8 cast inside _pil_resize."""
    from PIL import Image

    img = Image.fromarray(arr.astype(np.uint8))
    rot = img.rotate(deg, resample=Image.BILINEAR,
                     expand=bool(zoom_out))
    out = np.asarray(rot, dtype=arr.dtype)
    h, w = arr.shape[:2]
    if zoom_out:
        # uniform scale so the whole rotated frame fits, then center-pad
        # back to (h, w) — resizing straight to (w, h) would stretch
        # non-square images
        rh, rw = out.shape[:2]
        s = min(h / rh, w / rw)
        sh, sw = max(1, int(rh * s)), max(1, int(rw * s))
        scaled = _pil_resize(out, (sw, sh), Image.BILINEAR)
        canvas = np.zeros((h, w) + arr.shape[2:], dtype=arr.dtype)
        y0, x0 = (h - sh) // 2, (w - sw) // 2
        canvas[y0:y0 + sh, x0:x0 + sw] = scaled
        out = canvas
    elif zoom_in:
        # largest axis-aligned rectangle with the original aspect ratio
        # inside the rotated frame (theta clamped to [0, 90deg], so the
        # sin+cos denominators are >= 1)
        theta = abs(deg) % 180
        theta = min(theta, 180 - theta) * np.pi / 180.0
        s, c = abs(np.sin(theta)), abs(np.cos(theta))
        scale = min(h / (w * s + h * c), w / (h * s + w * c))
        ch, cw = max(1, int(h * scale)), max(1, int(w * scale))
        y0, x0 = (h - ch) // 2, (w - cw) // 2
        out = _pil_resize(out[y0:y0 + ch, x0:x0 + cw], (w, h),
                          Image.BILINEAR).astype(arr.dtype)
    return out


class Rotate(Block):
    """Rotates by a fixed angle in degrees (ref: transforms.py —
    Rotate)."""

    def __init__(self, rotation_degrees, zoom_in=False, zoom_out=False):
        super().__init__()
        if zoom_in and zoom_out:
            raise MXNetError("zoom_in and zoom_out are exclusive")
        self._deg = rotation_degrees
        self._zoom_in = zoom_in
        self._zoom_out = zoom_out

    def forward(self, x):
        return _nd.array(_rotate_np(_to_np(x), self._deg,
                                    self._zoom_in, self._zoom_out))


class RandomRotation(Block):
    """Rotates by an angle drawn from ``angle_limits``
    (ref: transforms.py — RandomRotation)."""

    def __init__(self, angle_limits, zoom_in=False, zoom_out=False,
                 rotate_with_proba=1.0):
        super().__init__()
        lo, hi = angle_limits
        if lo >= hi:
            raise MXNetError("angle_limits must be (low, high) with "
                             "low < high")
        if not 0 <= rotate_with_proba <= 1:
            raise MXNetError("rotate_with_proba must be in [0, 1]")
        if zoom_in and zoom_out:
            raise MXNetError("zoom_in and zoom_out are exclusive")
        self._limits = (lo, hi)
        self._proba = rotate_with_proba
        self._zoom_in = zoom_in
        self._zoom_out = zoom_out

    def forward(self, x):
        if np.random.random() > self._proba:
            return x if isinstance(x, NDArray) else _nd.array(_to_np(x))
        deg = np.random.uniform(*self._limits)
        return _nd.array(_rotate_np(_to_np(x), deg,
                                    self._zoom_in, self._zoom_out))
