"""Vision datasets (ref: python/mxnet/gluon/data/vision/datasets.py).

No network in this environment: datasets read standard-format files from a
local ``root`` (idx-gz for MNIST/FashionMNIST, python pickles for CIFAR,
.rec for ImageRecordDataset) and raise a clear error if absent — the
reference's auto-download step is the only part dropped.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as np

from ....base import MXNetError
from ....ndarray import ndarray as _nd
from ..dataset import Dataset, RecordFileDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._transform = transform
        self._data = None
        self._label = None
        self._root = os.path.expanduser(root)
        if not os.path.isdir(self._root):
            raise MXNetError(
                "dataset root %s does not exist (no network in this build: "
                "place the dataset files there manually)" % self._root)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from idx files (ref: datasets.py — MNIST)."""

    _train_files = ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz")
    _test_files = ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz")

    def __init__(self, root="~/.mxnet/datasets/mnist", train=True,
                 transform=None):
        self._train = train
        super().__init__(root, transform)

    def _read_idx(self, path):
        opener = gzip.open if path.endswith(".gz") else open
        if not os.path.exists(path):
            base, ext = os.path.splitext(path)
            alt = base if ext == ".gz" else path + ".gz"
            if os.path.exists(alt):
                path = alt
            else:
                raise MXNetError("dataset file %s not found" % path)
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic = struct.unpack(">I", f.read(4))[0]
            ndim = magic & 0xFF
            shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
            return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)

    def _get_data(self):
        img_f, lbl_f = self._train_files if self._train else self._test_files
        images = self._read_idx(os.path.join(self._root, img_f))
        labels = self._read_idx(os.path.join(self._root, lbl_f))
        self._data = images.reshape(-1, 28, 28, 1)
        self._label = labels.astype(np.int32)


class FashionMNIST(MNIST):
    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 from the python-pickle batches (ref: datasets.py — CIFAR10)."""

    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True,
                 transform=None):
        self._train = train
        super().__init__(root, transform)

    def _load_batches(self, names):
        data, labels = [], []
        for name in names:
            path = os.path.join(self._root, name)
            if not os.path.exists(path):
                # allow the cifar-10-batches-py subdir layout
                alt = os.path.join(self._root, "cifar-10-batches-py", name)
                if os.path.exists(alt):
                    path = alt
                else:
                    raise MXNetError("dataset file %s not found" % path)
            with open(path, "rb") as f:
                batch = pickle.load(f, encoding="latin1")
            data.append(np.asarray(batch["data"], dtype=np.uint8))
            labels.extend(batch.get("labels", batch.get("fine_labels")))
        data = np.concatenate(data).reshape(-1, 3, 32, 32)
        return data.transpose(0, 2, 3, 1), np.asarray(labels, dtype=np.int32)

    def _get_data(self):
        if self._train:
            names = ["data_batch_%d" % i for i in range(1, 6)]
        else:
            names = ["test_batch"]
        self._data, self._label = self._load_batches(names)


class CIFAR100(CIFAR10):
    def __init__(self, root="~/.mxnet/datasets/cifar100", fine_label=True,
                 train=True, transform=None):
        self._fine = fine_label
        super().__init__(root, train, transform)

    def _get_data(self):
        name = "train" if self._train else "test"
        path = os.path.join(self._root, name)
        if not os.path.exists(path):
            alt = os.path.join(self._root, "cifar-100-python", name)
            if os.path.exists(alt):
                path = alt
            else:
                raise MXNetError("dataset file %s not found" % path)
        with open(path, "rb") as f:
            batch = pickle.load(f, encoding="latin1")
        data = np.asarray(batch["data"], dtype=np.uint8).reshape(-1, 3, 32, 32)
        self._data = data.transpose(0, 2, 3, 1)
        key = "fine_labels" if self._fine else "coarse_labels"
        self._label = np.asarray(batch[key], dtype=np.int32)


class ImageRecordDataset(RecordFileDataset):
    """Images in a .rec file (ref: datasets.py — ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from ....recordio import unpack_img

        record = super().__getitem__(idx)
        header, img = unpack_img(record, self._flag)
        label = header.label
        if isinstance(label, np.ndarray) and label.size == 1:
            label = float(label[0])
        img = _nd.array(img)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(Dataset):
    """root/category/image.jpg layout (ref: datasets.py — ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                if os.path.splitext(filename)[1].lower() in self._exts:
                    self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        from PIL import Image

        path, label = self.items[idx]
        img = Image.open(path)
        img = img.convert("RGB" if self._flag else "L")
        img = _nd.array(np.asarray(img))
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
