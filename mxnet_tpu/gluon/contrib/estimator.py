"""Estimator — high-level Gluon training facade
(ref: python/mxnet/gluon/contrib/estimator/estimator.py +
event_handler.py, ≥1.5). fit() drives epochs over a DataLoader with an
event-handler pipeline (train begin/end, epoch begin/end, batch
begin/end); handlers cover metric logging, validation, checkpointing,
and early stopping — the same surface the reference ships.
"""
from __future__ import annotations

import copy
import logging
import time

from ... import autograd
from ... import engine as _engine
from ...base import MXNetError
from ... import metric as metric_mod
from ..trainer import Trainer

__all__ = ["Estimator", "EventHandler", "LoggingHandler",
           "CheckpointHandler", "EarlyStoppingHandler", "StopTraining"]


class StopTraining(Exception):
    """Raised by a handler to end fit() early (ref: event_handler.py)."""


class EventHandler:
    """Base handler — override any subset of the six events
    (ref: event_handler.py — EventHandler mixins)."""

    def train_begin(self, estimator):
        pass

    def train_end(self, estimator):
        pass

    def epoch_begin(self, estimator):
        pass

    def epoch_end(self, estimator):
        pass

    def batch_begin(self, estimator):
        pass

    def batch_end(self, estimator):
        pass

    def workers_lost(self, estimator):
        """Fired when the dist kvstore's membership reaper declares one
        or more workers dead (estimator.lost_workers holds the running
        total; sync reductions have degraded to the survivors)."""
        pass


class LoggingHandler(EventHandler):
    """Log metrics every `log_interval` batches + per epoch
    (ref: event_handler.py — LoggingHandler)."""

    def __init__(self, log_interval=50, logger=None):
        self.log_interval = log_interval
        self.logger = logger or logging.getLogger("estimator")

    def train_begin(self, estimator):
        self._tic = time.time()

    def epoch_begin(self, estimator):
        self._tic = time.time()

    def batch_end(self, estimator):
        if estimator.batch_idx % self.log_interval == 0:
            msgs = ["%s=%.4f" % m.get() for m in estimator.train_metrics]
            self.logger.info("epoch %d batch %d %s", estimator.epoch,
                             estimator.batch_idx, " ".join(msgs))

    def epoch_end(self, estimator):
        msgs = ["train %s=%.4f" % m.get() for m in estimator.train_metrics]
        msgs += ["val %s=%.4f" % m.get() for m in estimator.val_metrics
                 if m.num_inst]
        self.logger.info("epoch %d done (%.1fs): %s", estimator.epoch,
                         time.time() - self._tic, " ".join(msgs))

    def workers_lost(self, estimator):
        self.logger.warning(
            "epoch %d batch %d: membership declared worker(s) dead "
            "(%d lost so far) — training degrades over the survivors",
            estimator.epoch, estimator.batch_idx, estimator.lost_workers)


def _default_monitor(estimator):
    """Prefer a validation metric that actually saw data (val_metrics are
    always allocated but stay empty without val_data), else train."""
    for m in estimator.val_metrics:
        if m.num_inst:
            return m
    return estimator.train_metrics[0]


def _resolve_mode(mode, metric):
    """'auto' infers the improvement direction from the metric name the way
    the reference's handlers do (ref: event_handler.py — mode='auto':
    loss/error-like monitors minimize, everything else maximizes)."""
    if mode != "auto":
        return mode
    name = metric.get()[0]
    name = name[0] if isinstance(name, (list, tuple)) else name
    lowered = str(name).lower()
    if any(k in lowered for k in ("loss", "error", "perplexity", "mae",
                                  "mse", "rmse")):
        return "min"
    return "max"


class CheckpointHandler(EventHandler):
    """Save parameters each epoch, optionally only on metric improvement
    (ref: event_handler.py — CheckpointHandler). mode: "auto" (default)
    infers the direction from the monitor's name — loss-like monitors
    minimize, accuracy-like maximize; "max"/"min" force it.

    ``full_state=True`` upgrades the per-epoch save from bare params to
    an atomic full-training-state checkpoint (params + trainer/optimizer
    state + epoch cursor + loss-scale + PRNG, one CRC'd manifest —
    resilience.CheckpointManager), rotated to the last ``keep_last``.
    With ``resume_from_checkpoint=True`` a killed run restarts where it
    left off: ``train_begin`` restores the newest valid checkpoint and
    fast-forwards ``estimator.epoch``, so ``fit(epochs=N)`` trains the
    REMAINING epochs of the original schedule."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 save_best=False, mode="auto", full_state=False,
                 resume_from_checkpoint=False, keep_last=3):
        import os

        os.makedirs(model_dir, exist_ok=True)
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.mode = mode
        self.full_state = full_state
        self.resume_from_checkpoint = resume_from_checkpoint
        self.keep_last = keep_last
        self._best = None
        self._manager = None

    def _mgr(self, estimator):
        if self._manager is None:
            from ...resilience import CheckpointManager

            self._manager = CheckpointManager(
                self.model_dir, net=estimator.net,
                trainer=estimator.trainer, prefix=self.model_prefix,
                keep_last=self.keep_last)
        return self._manager

    def train_begin(self, estimator):
        if self.full_state and self.resume_from_checkpoint:
            state = self._mgr(estimator).resume()
            if state is not None:
                # fires before fit() reads its start epoch, so the loop
                # continues right after the last completed epoch
                estimator.epoch = state.epoch + 1

    def epoch_end(self, estimator):
        import os

        if self.full_state:
            self._mgr(estimator).save(epoch=estimator.epoch,
                                      step=estimator.epoch + 1)
            return
        path = os.path.join(self.model_dir, "%s-%04d.params"
                            % (self.model_prefix, estimator.epoch))
        if not self.save_best:
            estimator.net.save_parameters(path)
            return
        metric = self.monitor or _default_monitor(estimator)
        mode = _resolve_mode(self.mode, metric)
        _, value = metric.get()
        improved = self._best is None or (
            value > self._best if mode == "max" else value < self._best)
        if improved:
            self._best = value
            estimator.net.save_parameters(os.path.join(
                self.model_dir, "%s-best.params" % self.model_prefix))


class EarlyStoppingHandler(EventHandler):
    """Stop when the monitored metric stops improving
    (ref: event_handler.py — EarlyStoppingHandler)."""

    def __init__(self, monitor=None, min_delta=0.0, patience=0,
                 mode="auto"):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.mode = mode
        self._best = None
        self._wait = 0

    def epoch_end(self, estimator):
        metric = self.monitor or _default_monitor(estimator)
        mode = _resolve_mode(self.mode, metric)
        _, value = metric.get()
        improved = (self._best is None
                    or (mode == "max"
                        and value > self._best + self.min_delta)
                    or (mode == "min"
                        and value < self._best - self.min_delta))
        if improved:
            self._best = value
            self._wait = 0
        else:
            self._wait += 1
            if self._wait > self.patience:
                raise StopTraining(
                    "no improvement for %d epochs (best %.4f)"
                    % (self._wait, self._best))


class Estimator:
    """fit/evaluate facade over net + loss + trainer
    (ref: estimator.py — Estimator).

    Usage::

        est = Estimator(net, loss, metrics=mx.metric.Accuracy(),
                        trainer=trainer)
        est.fit(train_loader, val_data=val_loader, epochs=3)
    """

    def __init__(self, net, loss, metrics=None, trainer=None, context=None):
        del context  # device placement is XLA's job in this build
        self.net = net
        self.loss = loss
        metrics = metrics if isinstance(metrics, (list, tuple)) else (
            [metrics] if metrics is not None else [])
        for m in metrics:
            if not isinstance(m, metric_mod.EvalMetric):
                raise MXNetError("metrics must be EvalMetric instances, "
                                 "got %r" % (m,))
            if isinstance(m, metric_mod.CompositeEvalMetric):
                raise MXNetError(
                    "pass the child metrics as a list instead of a "
                    "CompositeEvalMetric — the handler pipeline reads "
                    "each metric's (name, value) individually")
        self.train_metrics = list(metrics) or [metric_mod.Loss("loss")]
        # deepcopy keeps each metric's configuration (top_k, axis, ...);
        # a bare type(m)() would silently revert it
        self.val_metrics = [copy.deepcopy(m) for m in self.train_metrics]
        for m in self.val_metrics:
            m.reset()
        if trainer is None:
            trainer = Trainer(net.collect_params(), "adam",
                              {"learning_rate": 1e-3})
        self.trainer = trainer
        self.epoch = 0
        self.batch_idx = 0
        self.lost_workers = 0  # membership deaths observed (dist kvstore)

    # ------------------------------------------------------------------
    @staticmethod
    def _batches(data):
        """Support re-iterable sequences, DataLoaders, and DataIter-style
        objects (DataIter must be reset between epochs; its batches carry
        .data/.label lists instead of being (x, y) tuples)."""
        if hasattr(data, "reset"):
            data.reset()
        for batch in data:
            if hasattr(batch, "data") and hasattr(batch, "label"):
                d, l = batch.data[0], batch.label[0]
                pad = getattr(batch, "pad", 0) or 0
                if pad:  # strip wrap-around filler rows
                    d = d[:d.shape[0] - pad]
                    l = l[:l.shape[0] - pad]
                yield d, l
            else:
                yield batch[0], batch[1]

    def _update_metrics(self, metrics, labels, preds, loss):
        for m in metrics:
            if isinstance(m, metric_mod.Loss):
                m.update(None, [loss])
            else:
                m.update([labels], [preds])

    def _emit_epoch_telemetry(self, seconds):
        """Registry + sink output at epoch end (after the dispatch
        window drained, so counts describe every dispatched step): epoch
        duration histogram, batch counter, epoch gauge, one metrics
        snapshot row, and a sink flush — the JSONL file is durable at
        every epoch boundary."""
        from ... import telemetry

        telemetry.histogram(
            "mxt_estimator_epoch_seconds",
            "Wall-clock seconds per Estimator.fit epoch "
            "(train + validation).").observe(seconds)
        telemetry.counter(
            "mxt_estimator_batches_total",
            "Batches trained by Estimator.fit.").inc(self.batch_idx + 1)
        telemetry.gauge(
            "mxt_estimator_epoch",
            "Last completed Estimator.fit epoch.").set(self.epoch)
        telemetry.emit_event("epoch_end", epoch=self.epoch,
                             batches=self.batch_idx + 1,
                             seconds=round(seconds, 6))
        telemetry.flush(write_metrics=True)

    def evaluate(self, val_data):
        for m in self.val_metrics:
            m.reset()
        for data, label in self._batches(val_data):
            pred = self.net(data)
            loss = self.loss(pred, label)
            self._update_metrics(self.val_metrics, label, pred, loss)
        return [m.get() for m in self.val_metrics]

    def fit(self, train_data, val_data=None, epochs=1, event_handlers=None,
            batches=None):
        handlers = list(event_handlers or [])
        if not any(isinstance(h, LoggingHandler) for h in handlers):
            handlers.append(LoggingHandler())

        def fire(event):
            for h in handlers:
                getattr(h, event)(self)

        fire("train_begin")
        start = self.epoch
        epoch_trained = False  # did the current epoch finish its batches?
        try:
            for self.epoch in range(start, start + epochs):
                epoch_trained = False
                epoch_t0 = time.perf_counter()
                for m in self.train_metrics:
                    m.reset()
                fire("epoch_begin")
                for self.batch_idx, (data, label) in enumerate(
                        self._batches(train_data)):
                    fire("batch_begin")
                    with autograd.record():
                        pred = self.net(data)
                        loss = self.loss(pred, label)
                    loss.backward()
                    batch_size = data.shape[0]
                    self.trainer.step(batch_size)
                    self._update_metrics(self.train_metrics, label, pred,
                                         loss)
                    # elastic membership: surface reaper-declared deaths
                    # as an estimator event (reads the heartbeat-cached
                    # count — no extra network traffic per batch)
                    kv = getattr(self.trainer, "_kvstore", None)
                    if kv is not None and hasattr(kv, "lost_workers"):
                        lost = kv.lost_workers()
                        if lost > self.lost_workers:
                            self.lost_workers = lost
                            from ... import telemetry
                            telemetry.emit_event(
                                "workers_lost", epoch=self.epoch,
                                batch=self.batch_idx,
                                lost_total=self.lost_workers)
                            fire("workers_lost")
                    fire("batch_end")
                    if batches is not None and self.batch_idx + 1 >= batches:
                        break
                # drain the async dispatch window so epoch-end handlers
                # (checkpointing, logging, early stop) observe caught-up
                # counters and final weights — the per-batch loop itself
                # never forces a host read (metrics accumulate on device)
                _engine.wait_all()
                if val_data is not None:
                    self.evaluate(val_data)
                epoch_trained = True
                self._emit_epoch_telemetry(
                    time.perf_counter() - epoch_t0)
                fire("epoch_end")
            self.epoch = start + epochs  # a second fit() resumes here
        except StopTraining as e:
            if epoch_trained:  # raised from epoch_end: epoch completed
                self.epoch += 1
            # else (raised mid-epoch): resume repeats the cut epoch
            logging.getLogger("estimator").info("early stop: %s", e)
        _engine.wait_all()
        fire("train_end")
        return self
