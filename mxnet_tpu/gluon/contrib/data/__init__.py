"""gluon.contrib.data (ref: python/mxnet/gluon/contrib/data)."""
from . import sampler, text
from .sampler import IntervalSampler
from .text import WikiText2, WikiText103

__all__ = ["sampler", "text", "IntervalSampler", "WikiText2",
           "WikiText103"]
