"""Experimental text datasets (ref: python/mxnet/gluon/contrib/data/
text.py — WikiText2/WikiText103). The parsing/vocabulary/sequence logic
is fully functional over a local copy of the corpus; the fetch goes
through gluon.utils.download which raises loudly without egress unless
the archive is already cached."""
from __future__ import annotations

import io
import os
import zipfile

import numpy as np

from ....contrib.text.utils import count_tokens_from_str
from ....contrib.text.vocab import Vocabulary
from ...data.dataset import Dataset
from ...utils import download

__all__ = ["WikiText2", "WikiText103"]


class _WikiText(Dataset):
    """Token-id sequences of fixed length ``seq_len`` over the corpus
    (ref: text.py — _WikiText; layout matches the reference: flatten the
    whole split, chop into (seq_len+1)-grams: data=x[:-1], label=x[1:])."""

    archive = ""
    url_root = "https://s3.amazonaws.com/research.metamind.io/wikitext/"
    namespace = ""

    def __init__(self, root, segment, vocab, seq_len):
        self._root = os.path.expanduser(root)
        self._segment = segment
        self._seq_len = seq_len
        os.makedirs(self._root, exist_ok=True)
        raw = self._read_segment()
        counter = count_tokens_from_str(raw)
        self.vocabulary = vocab if vocab is not None else Vocabulary(
            counter, unknown_token="<unk>", reserved_tokens=["<eos>"])
        ids = np.asarray(
            self.vocabulary.to_indices(
                raw.replace("\n", " <eos> ").split()),
            dtype=np.int32)
        n = (len(ids) - 1) // seq_len
        self._data = ids[:n * seq_len].reshape(n, seq_len)
        self._label = ids[1:n * seq_len + 1].reshape(n, seq_len)

    def _read_segment(self):
        fname = "wiki.%s.tokens" % self._segment
        member = "%s/%s" % (self.namespace, fname)
        path = os.path.join(self._root, fname)
        if not os.path.isfile(path):
            zpath = download(self.url_root + self.archive,
                             path=os.path.join(self._root, self.archive))
            with zipfile.ZipFile(zpath) as zf:
                with zf.open(member) as src, open(path, "wb") as dst:
                    dst.write(src.read())
        with io.open(path, "r", encoding="utf8") as f:
            return f.read()

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        from .... import ndarray as nd

        return nd.array(self._data[idx]), nd.array(self._label[idx])


class WikiText2(_WikiText):
    """ref: text.py — WikiText2 (segments: train/val/test)."""

    archive = "wikitext-2-v1.zip"
    namespace = "wikitext-2"

    def __init__(self, root="~/.mxnet_tpu/datasets/wikitext-2",
                 segment="train", vocab=None, seq_len=35):
        super().__init__(root, segment, vocab, seq_len)


class WikiText103(_WikiText):
    """ref: text.py — WikiText103."""

    archive = "wikitext-103-v1.zip"
    namespace = "wikitext-103"

    def __init__(self, root="~/.mxnet_tpu/datasets/wikitext-103",
                 segment="train", vocab=None, seq_len=35):
        super().__init__(root, segment, vocab, seq_len)
