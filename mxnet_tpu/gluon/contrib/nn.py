"""Experimental Gluon layers (ref: python/mxnet/gluon/contrib/nn/
basic_layers.py). In the reference SyncBatchNorm lives here; our
implementation sits in gluon.nn (it is a first-class citizen on a
sharded backend) and is re-exported for import parity."""
from __future__ import annotations

from ..block import Block, HybridBlock
from ..nn.basic_layers import HybridSequential, Sequential, SyncBatchNorm
from ..nn.conv_layers import _tup

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "PixelShuffle1D", "PixelShuffle2D",
           "PixelShuffle3D"]


class Concurrent(Sequential):
    """Feeds the input to every child and concatenates the outputs along
    ``axis`` (ref: basic_layers.py — Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x, *args):
        from ... import ndarray as F

        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class HybridConcurrent(HybridSequential):
    """Hybridizable :class:`Concurrent` (ref: basic_layers.py)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class Identity(HybridBlock):
    """Identity mapping — useful as a :class:`Concurrent` branch
    (ref: basic_layers.py — Identity)."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Block):
    """Embedding with a row_sparse gradient, for sparse training through
    the KVStore row_sparse path (ref: basic_layers.py — SparseEmbedding).
    Identical compute to ``nn.Embedding(sparse_grad=True)``; kept as a
    distinct class for reference API parity. The weight is registered
    directly (param name ``weight``) so checkpoints match the
    reference's parameter layout."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "sparse_grad": True}
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, grad_stype="row_sparse")

    def forward(self, x):
        from ... import ndarray as F

        return F.Embedding(x, self.weight.data(), **self._kwargs)

    def __repr__(self):
        return "SparseEmbedding({input_dim} -> {output_dim})".format(
            **self._kwargs)


class _PixelShuffle(HybridBlock):
    """Rearranges channel blocks into spatial dims — sub-pixel conv
    upsampling (ref: basic_layers.py — PixelShuffle1D/2D/3D; Shi et al.
    1609.05158). Written with the reference's shape-free reshape codes
    (0 keep / -3 merge / -4 split) so the blocks trace symbolically
    (export/SymbolBlock) as well as eagerly; XLA lowers the
    reshape/transpose chain to a single copy on TPU."""

    def __init__(self, factor, ndim, **kwargs):
        super().__init__(**kwargs)
        self._factors = tuple(int(f) for f in _tup(factor, ndim))
        assert len(self._factors) == ndim, (factor, ndim)

    def __call__(self, x, *args):
        # eager path: fail with a clear message instead of an opaque
        # backend reshape error (Symbols have no shape; checked at bind)
        shape = getattr(x, "shape", None)
        if shape is not None and len(shape) >= 2:
            prod = 1
            for f in self._factors:
                prod *= f
            if shape[1] % prod != 0:
                raise ValueError(
                    "channels %d not divisible by product of factors %s"
                    % (shape[1], self._factors))
        return super().__call__(x, *args)

    def __repr__(self):
        return "%s(%s)" % (type(self).__name__, self._factors)


class PixelShuffle1D(_PixelShuffle):
    def __init__(self, factor, **kwargs):
        super().__init__(factor, 1, **kwargs)

    def hybrid_forward(self, F, x):
        f, = self._factors                       # (N, C*f, W)
        x = F.reshape(x, shape=(0, -4, -1, f, 0))      # (N, C, f, W)
        x = F.transpose(x, axes=(0, 1, 3, 2))    # (N, C, W, f)
        return F.reshape(x, shape=(0, 0, -3))          # (N, C, W*f)


class PixelShuffle2D(_PixelShuffle):
    def __init__(self, factor, **kwargs):
        super().__init__(factor, 2, **kwargs)

    def hybrid_forward(self, F, x):
        f1, f2 = self._factors                          # (N, C*f1*f2, H, W)
        x = F.reshape(x, shape=(0, -4, -1, f1 * f2, 0, 0))    # (N, C, f1*f2, H, W)
        x = F.reshape(x, shape=(0, 0, -4, f1, f2, 0, 0))      # (N, C, f1, f2, H, W)
        x = F.transpose(x, axes=(0, 1, 4, 2, 5, 3))     # (N, C, H, f1, W, f2)
        return F.reshape(x, shape=(0, 0, -3, -3))             # (N, C, H*f1, W*f2)


class PixelShuffle3D(_PixelShuffle):
    def __init__(self, factor, **kwargs):
        super().__init__(factor, 3, **kwargs)

    def hybrid_forward(self, F, x):
        f1, f2, f3 = self._factors               # (N, C*f1*f2*f3, D, H, W)
        # split the factor block off C, then interleave each factor with
        # its spatial dim, merging as we go
        x = F.reshape(x, shape=(0, -4, -1, f1 * f2 * f3, 0, 0, 0))
        x = F.swapaxes(x, dim1=2, dim2=3)                  # (N, C, D, f1*f2*f3, H, W)
        x = F.reshape(x, shape=(0, 0, 0, -4, f1, f2 * f3, 0, 0))
        x = F.reshape(x, shape=(0, 0, -3, 0, 0, 0))    # (N, C, D*f1, f2*f3, H, W)
        x = F.swapaxes(x, dim1=3, dim2=4)                  # (N, C, D*f1, H, f2*f3, W)
        x = F.reshape(x, shape=(0, 0, 0, 0, -4, f2, f3, 0))
        x = F.reshape(x, shape=(0, 0, 0, -3, 0, 0))    # (N, C, D*f1, H*f2, f3, W)
        x = F.swapaxes(x, dim1=4, dim2=5)                  # (N, C, D*f1, H*f2, W, f3)
        return F.reshape(x, shape=(0, 0, 0, 0, -3))    # (N, C, D*f1, H*f2, W*f3)
