"""gluon.contrib (ref: python/mxnet/gluon/contrib/__init__.py)."""
from . import estimator, nn, rnn
from .estimator import Estimator

__all__ = ["estimator", "Estimator", "nn", "rnn"]
