"""gluon.contrib (ref: python/mxnet/gluon/contrib/__init__.py)."""
from . import data, estimator, nn, rnn
from .estimator import Estimator

__all__ = ["data", "estimator", "Estimator", "nn", "rnn"]
