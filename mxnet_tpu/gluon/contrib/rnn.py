"""Experimental recurrent cells (ref: python/mxnet/gluon/contrib/rnn/
{rnn_cell.py,conv_rnn_cell.py}): VariationalDropoutCell, LSTMPCell, and
the Conv1D/2D/3D RNN/LSTM/GRU cell family.

Conv cells keep the reference's contract: ``input_shape`` is the
per-step (C, *spatial) shape, h2h convs are same-padded (odd kernels
required), gate math matches the dense cells. Each step is a pair of
convs + elementwise gates — XLA fuses the gate arithmetic into the conv
epilogue, so a cell step is two MXU convolutions."""
from __future__ import annotations

from ... import autograd
from ..nn.conv_layers import _tup
from ..rnn.rnn_cell import HybridRecurrentCell, ModifierCell

__all__ = ["VariationalDropoutCell", "LSTMPCell",
           "Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


class VariationalDropoutCell(ModifierCell):
    """Applies Gal & Ghahramani (1512.05287) variational dropout: one
    mask per sequence, shared across all time steps, separately for
    inputs / states / outputs (ref: rnn_cell.py —
    VariationalDropoutCell)."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None
        super().__init__(base_cell)

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def _initialize_masks(self, F, inputs, states):
        if not autograd.is_training():
            return
        if self.drop_inputs and self._input_mask is None:
            self._input_mask = F.Dropout(F.ones_like(inputs),
                                         p=self.drop_inputs,
                                         train_mode=True)
        if self.drop_states and self._state_mask is None:
            self._state_mask = F.Dropout(F.ones_like(states[0]),
                                         p=self.drop_states,
                                         train_mode=True)

    def hybrid_forward(self, F, inputs, states):
        cell = self.base_cell
        self._initialize_masks(F, inputs, states)
        if self._input_mask is not None:
            inputs = inputs * self._input_mask
        if self._state_mask is not None:
            states = [states[0] * self._state_mask] + list(states[1:])
        next_output, next_states = cell(inputs, states)
        if self.drop_outputs:
            if autograd.is_training():
                if self._output_mask is None:
                    self._output_mask = F.Dropout(F.ones_like(next_output),
                                                  p=self.drop_outputs,
                                                  train_mode=True)
                next_output = next_output * self._output_mask
        return next_output, next_states

class LSTMPCell(HybridRecurrentCell):
    """LSTM with a projection of the hidden state (LSTMP, Sak et al.
    1402.1128) — the recurrent/output state is ``projection_size`` wide
    while the cell state stays ``hidden_size`` (ref: rnn_cell.py —
    LSTMPCell; gate order [i,f,g,o])."""

    def __init__(self, hidden_size, projection_size,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, projection_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.h2r_weight = self.params.get(
                "h2r_weight", shape=(projection_size, hidden_size),
                init=h2r_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "lstmp"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight=None,
                       h2h_weight=None, h2r_weight=None, i2h_bias=None,
                       h2h_bias=None):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        sg = F.split(gates, num_outputs=4, axis=1)
        in_gate = F.sigmoid(sg[0])
        forget_gate = F.sigmoid(sg[1])
        in_transform = F.tanh(sg[2])
        out_gate = F.sigmoid(sg[3])
        next_c = forget_gate * states[1] + in_gate * in_transform
        hidden = out_gate * F.tanh(next_c)
        next_r = F.FullyConnected(hidden, h2r_weight, None, no_bias=True,
                                  num_hidden=self._projection_size)
        return next_r, [next_r, next_c]


class _BaseConvRNNCell(HybridRecurrentCell):
    """Shared machinery for convolutional recurrent cells
    (ref: conv_rnn_cell.py — _BaseConvRNNCell)."""

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, i2h_dilate, h2h_dilate, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, dims, conv_layout, activation,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_channels = hidden_channels
        self._input_shape = tuple(input_shape)
        self._conv_layout = conv_layout
        self._activation = activation
        self._dims = dims
        # channel position within the per-step (batchless) shape; the
        # reference derives it the same way (conv_rnn_cell.py —
        # conv_layout.find('C'))
        self._c_axis = conv_layout.find("C") - 1
        assert 0 <= self._c_axis <= dims, conv_layout
        assert len(self._input_shape) == dims + 1, \
            "input_shape must be the per-step (channels+spatial) shape"

        def _ntup(x, name):
            t = _tup(x, dims)
            assert len(t) == dims, "%s must have %d elements" % (name, dims)
            return t

        self._i2h_kernel = _ntup(i2h_kernel, "i2h_kernel")
        self._h2h_kernel = _ntup(h2h_kernel, "h2h_kernel")
        assert all(k % 2 == 1 for k in self._h2h_kernel), \
            "h2h_kernel must be odd (same-padded recurrence): %s" % (
                self._h2h_kernel,)
        self._i2h_pad = _ntup(i2h_pad, "i2h_pad")
        self._i2h_dilate = _ntup(i2h_dilate, "i2h_dilate")
        self._h2h_dilate = _ntup(h2h_dilate, "h2h_dilate")
        self._h2h_pad = tuple(d * (k - 1) // 2 for d, k in
                              zip(self._h2h_dilate, self._h2h_kernel))

        in_channels = self._input_shape[self._c_axis]
        ng = self._num_gates
        spatial_out = self._spatial_out()
        state = list(spatial_out)
        state.insert(self._c_axis, hidden_channels)
        self._state_shape = tuple(state)
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight",
                shape=(ng * hidden_channels, in_channels) + self._i2h_kernel,
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight",
                shape=(ng * hidden_channels, hidden_channels)
                + self._h2h_kernel,
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(ng * hidden_channels,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(ng * hidden_channels,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def _spatial_out(self):
        spatial = [s for i, s in enumerate(self._input_shape)
                   if i != self._c_axis]
        out = []
        for i, s in enumerate(spatial):
            k = self._i2h_dilate[i] * (self._i2h_kernel[i] - 1) + 1
            out.append((s + 2 * self._i2h_pad[i] - k) + 1)
        return tuple(out)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size,) + self._state_shape,
                 "__layout__": self._conv_layout}
                for _ in range(self._num_states)]

    def _conv_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                      i2h_bias, h2h_bias):
        ng = self._num_gates
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, pad=self._i2h_pad,
                            dilate=self._i2h_dilate,
                            num_filter=ng * self._hidden_channels,
                            layout=self._conv_layout)
        h2h = F.Convolution(states[0], h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, pad=self._h2h_pad,
                            dilate=self._h2h_dilate,
                            num_filter=ng * self._hidden_channels,
                            layout=self._conv_layout)
        return i2h, h2h

    def _act(self, F, x):
        return F.Activation(x, act_type=self._activation)


class _ConvRNNCell(_BaseConvRNNCell):
    _num_gates = 1
    _num_states = 1

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight=None,
                       h2h_weight=None, i2h_bias=None, h2h_bias=None):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        output = self._act(F, i2h + h2h)
        return output, [output]


class _ConvLSTMCell(_BaseConvRNNCell):
    """Shi et al. 1506.04214 (ConvLSTM); gate order [i,f,g,o]."""

    _num_gates = 4
    _num_states = 2

    def _alias(self):
        return "conv_lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight=None,
                       h2h_weight=None, i2h_bias=None, h2h_bias=None):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        gates = i2h + h2h
        c_axis = self._conv_layout.find("C")
        sg = F.split(gates, num_outputs=4, axis=c_axis)
        in_gate = F.sigmoid(sg[0])
        forget_gate = F.sigmoid(sg[1])
        in_transform = self._act(F, sg[2])
        out_gate = F.sigmoid(sg[3])
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * self._act(F, next_c)
        return next_h, [next_h, next_c]


class _ConvGRUCell(_BaseConvRNNCell):
    _num_gates = 3
    _num_states = 1

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight=None,
                       h2h_weight=None, i2h_bias=None, h2h_bias=None):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        c_axis = self._conv_layout.find("C")
        i2h_r, i2h_z, i2h_n = F.split(i2h, num_outputs=3, axis=c_axis)
        h2h_r, h2h_z, h2h_n = F.split(h2h, num_outputs=3, axis=c_axis)
        reset_gate = F.sigmoid(i2h_r + h2h_r)
        update_gate = F.sigmoid(i2h_z + h2h_z)
        next_h_tmp = self._act(F, i2h_n + reset_gate * h2h_n)
        next_h = ((1.0 - update_gate) * next_h_tmp
                  + update_gate * states[0])
        return next_h, [next_h]


def _make_conv_cell(base, dims, default_layout, alias_suffix):
    class Cell(base):
        def __init__(self, input_shape, hidden_channels, i2h_kernel,
                     h2h_kernel, i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                     i2h_weight_initializer=None,
                     h2h_weight_initializer=None,
                     i2h_bias_initializer="zeros",
                     h2h_bias_initializer="zeros",
                     conv_layout=default_layout, activation="tanh",
                     prefix=None, params=None):
            super().__init__(
                input_shape=input_shape, hidden_channels=hidden_channels,
                i2h_kernel=i2h_kernel, h2h_kernel=h2h_kernel,
                i2h_pad=i2h_pad, i2h_dilate=i2h_dilate,
                h2h_dilate=h2h_dilate,
                i2h_weight_initializer=i2h_weight_initializer,
                h2h_weight_initializer=h2h_weight_initializer,
                i2h_bias_initializer=i2h_bias_initializer,
                h2h_bias_initializer=h2h_bias_initializer,
                dims=dims, conv_layout=conv_layout, activation=activation,
                prefix=prefix, params=params)

    Cell.__name__ = "Conv%dD%sCell" % (dims, alias_suffix)
    Cell.__qualname__ = Cell.__name__
    Cell.__doc__ = ("%d-D convolutional %s cell (ref: conv_rnn_cell.py — "
                    "%s)." % (dims, alias_suffix, Cell.__name__))
    return Cell


Conv1DRNNCell = _make_conv_cell(_ConvRNNCell, 1, "NCW", "RNN")
Conv2DRNNCell = _make_conv_cell(_ConvRNNCell, 2, "NCHW", "RNN")
Conv3DRNNCell = _make_conv_cell(_ConvRNNCell, 3, "NCDHW", "RNN")
Conv1DLSTMCell = _make_conv_cell(_ConvLSTMCell, 1, "NCW", "LSTM")
Conv2DLSTMCell = _make_conv_cell(_ConvLSTMCell, 2, "NCHW", "LSTM")
Conv3DLSTMCell = _make_conv_cell(_ConvLSTMCell, 3, "NCDHW", "LSTM")
Conv1DGRUCell = _make_conv_cell(_ConvGRUCell, 1, "NCW", "GRU")
Conv2DGRUCell = _make_conv_cell(_ConvGRUCell, 2, "NCHW", "GRU")
Conv3DGRUCell = _make_conv_cell(_ConvGRUCell, 3, "NCDHW", "GRU")
