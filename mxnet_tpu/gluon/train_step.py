"""CachedTrainStep — the canonical Gluon train loop as ONE donated launch.

The reference's canonical loop

    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.step(batch_size)

pays one XLA launch for the hybridized forward, one per tape node for the
backward vjp walk (autograd.py — _run_backward), and one for the fused
optimizer update (gluon/trainer.py — _FusedUpdate). At ~3.4 ms per launch
on the axon tunnel (PERF.md §1.2) the backward walk alone dominates small
steps. ShardedTrainStep (parallel/sharded.py) already proves whole-step
fusion with buffer donation works here; CachedTrainStep brings the same
treatment to the single-device canonical path without asking the user to
leave the Gluon API: forward + loss + `jax.value_and_grad` over the
flattened parameter pytree + the per-parameter optimizer math
(`_FusedUpdate._param_update`, the exact kernels the eager Updater runs)
compile into ONE `jax.jit` program with weights, optimizer state, and aux
state donated. XLA's fuser then does the heavy lifting across the whole
step ("Operator Fusion in XLA", arXiv:2301.13062); donation gives the
in-place weight-update behavior of the weight-update treatment in
arXiv:2004.13336 on a single chip.

Aux states (BatchNorm running stats) ride the CachedOp rebind protocol
(gluon/block.py — _build_cached): the traced Parameter wrappers are
inspected after the forward and whatever they rebound to is returned as
extra (donated-in, written-back) outputs. The PRNG key is derived ON
DEVICE via fold_in(base_key, t), and all dynamic scalars (t, lr, wd,
rescale_grad) enter as traced 0-d arguments, so lr schedulers never
retrace.

Ineligible configurations (unsupported optimizer, sparse grads, dist
kvstore, multi-process, grad_req='add') fall back transparently to the
eager record/backward/step loop — same numerics, more launches. Gate:
``MXT_FUSED_STEP`` (default on, mirrors ``MXT_FUSED_TRAINER``).

With ``MXT_SKIP_NONFINITE=1`` the resilience non-finite guard compiles
INTO the program (resilience.py): a ``lax.cond`` makes the whole
weight/state/aux update the identity when any gradient is non-finite and
the step counter stays put. The flag is NOT read back per step: the step
count rides the program as a donated device scalar and the last 31 flags
as a device bitmask, so the host dispatches up to ``MXT_MAX_INFLIGHT``
steps ahead (engine.StepStream) and ONE deferred mask read retires a
whole window's bookkeeping — update counts, ``LossScaler.update_scale``,
the ``skipped_nonfinite_steps`` counter — without ever touching the
weights path (the skip is on-device, so numerics are bit-exact at any
window depth). An ``lr_scheduler`` makes the learning rate depend on the
data-dependent step count, so guard + scheduler forces the window to 1
(the pre-async per-step read).
"""
from __future__ import annotations

import time
from collections import OrderedDict

import jax

from ..base import MXNetError
from .. import autograd as ag
from .. import optimizer as opt
from .. import random as _random
from ..ndarray.ndarray import NDArray
from ..ndarray import ndarray as _nd
from .block import Block, _trace_depth
from .parameter import param_trace_scope
from .trainer import _FusedUpdate

__all__ = ["CachedTrainStep", "train_step", "FusedApply"]


def _config():
    from .. import config
    return config


def _count_launch():
    from .. import profiler
    profiler.record_launch()


class CachedTrainStep:
    """One donated XLA launch per training step for a Gluon block.

    Usage::

        trainer = gluon.Trainer(net.collect_params(), "adam",
                                {"learning_rate": 1e-3})
        step = trainer.fuse_step(net, loss_fn)   # or gluon.train_step(...)
        for x, y in loader:
            loss = step(x, y)                    # params update in place

    ``step(x, y, batch_size=None)`` is numerically identical to the
    canonical record/backward/step loop with ``batch_size`` defaulting to
    ``x.shape[batch_axis]`` (the gradient seed is ones over the loss —
    exactly what ``loss.backward()`` does — and the optimizer rescales by
    ``trainer._scale / batch_size``, exactly what ``trainer.step`` does).
    The returned loss has the same shape ``loss_fn`` produces.

    With ``return_outputs=True`` each call returns ``(loss, outputs)`` so
    metrics can be fed without a second forward — the outputs are extra
    results of the same single program, not another launch.

    Eligibility is decided once, lazily, on the first call (the trainer's
    kvstore decision and deferred parameter shapes must be resolved
    first); an ineligible config records ``fallback_reason`` and every
    call runs the eager loop instead — no exception, no retrace loop.
    A step that cannot run fused for transient reasons (uneven optimizer
    update counts left by a prior eager/kvstore path) also falls back,
    per step, and re-enters the fused path once counts are even again.
    """

    def __init__(self, net, loss_fn, trainer, batch_axis=0,
                 return_outputs=False):
        self._net = net
        self._loss_fn = loss_fn
        self._trainer = trainer
        self._batch_axis = batch_axis
        self._return_outputs = return_outputs
        self._jit = None
        self._fallback_reason = None
        self._base_key = None
        self._all_params = None
        self._train_names = None
        self._aux_names = None
        self._indices = None
        self._guard = False
        self._built_opt = None
        self._stream = None      # engine.StepStream (async dispatch window)
        self._t_dev = None       # device-carried step count (guard mode)
        self._mask_dev = None    # device-carried flag bitmask (guard mode)
        self._health = False     # stat row compiled into the program
        self._health_mon = None  # health.HealthMonitor (retirement consumer)
        self._spike = False      # grad_spike chaos rule compiled in
        self._hyper_cache = None  # (lr, wd, float(lr), float(wd))
        self._sig_recorded = False  # (x, y) signature saved for warmup
        self._hbm_published = False  # params/opt bytes in the HBM ledger

    # -- introspection ---------------------------------------------------
    @property
    def fused(self):
        """True once the fused program is built (first call succeeded)."""
        return self._jit is not None

    @property
    def fallback_reason(self):
        """Why the fused path is permanently unavailable (None if fused
        or not yet decided)."""
        return self._fallback_reason

    # -- eligibility -----------------------------------------------------
    @staticmethod
    def eligible(trainer, net):
        """Reason string if the whole-step fusion cannot be used, else
        None. Mirrors _FusedUpdate.eligible plus whole-step-specific
        constraints (grad_req='write' only; trainer params == net
        params). Call after the trainer's kvstore is initialized."""
        o = trainer._optimizer
        if not _config().get("MXT_FUSED_STEP"):
            return "MXT_FUSED_STEP=0"
        if type(o).__name__ not in _FusedUpdate._SUPPORTED or \
                type(o).__module__ != opt.Optimizer.__module__:
            return "optimizer %s has no fused whole-step builder" \
                % type(o).__name__
        if getattr(o, "multi_precision", False):
            return "multi_precision optimizer"
        if getattr(o, "aggregate_num", 0):
            return "aggregate_num optimizer"
        if trainer._update_on_kvstore:
            return "update_on_kvstore"
        kv = trainer._kvstore
        if kv is not None and (kv.type.startswith("dist") or
                               trainer._compression_params):
            return "distributed/compressed kvstore"
        if jax.process_count() > 1:
            return "multi-process"
        net_params = net.collect_params()
        trainable = {n for n, p in net_params.items()
                     if p.grad_req != "null"}
        for name, p in net_params.items():
            # mesh-sharded buffers (parallel.ShardedTrainStep placed them
            # with a multi-device NamedSharding) must not be DONATED into
            # this single-device program: XLA would silently gather them
            # back to one device and the next sharded step would pay a
            # full re-placement — the two step builders own disjoint nets
            d = p._data
            if d is not None:
                sh = getattr(d.data, "sharding", None)
                if sh is not None and len(getattr(sh, "device_set",
                                                  ())) > 1:
                    return "parameter %s is mesh-sharded (%d devices) — " \
                        "parallel.ShardedTrainStep owns sharded nets" \
                        % (name, len(sh.device_set))
        for name, p in net_params.items():
            if p.grad_req == "null":
                continue
            if p.grad_req != "write":
                return "grad_req=%r on %s (whole-step fusion computes " \
                    "fresh grads; accumulation needs the eager loop)" \
                    % (p.grad_req, name)
            if getattr(p, "_grad_stype", "default") != "default":
                return "sparse gradient on %s" % name
            if name not in trainer._param2idx:
                return "parameter %s not managed by this trainer" % name
        for p in trainer._params:
            if p.grad_req != "null" and p.name not in trainable:
                return "trainer manages parameter %s outside the net" \
                    % p.name
        return None

    # -- build -----------------------------------------------------------
    def _build(self, x):
        net, tr = self._net, self._trainer
        # resolve deferred shapes with one throwaway eager forward in
        # predict mode (the HybridBlock._ensure_initialized treatment,
        # generalized to plain Blocks)
        if any(p._deferred_init is not None
               for p in net.collect_params().values()):
            with ag.pause(train_mode=False):
                _trace_depth.depth += 1
                try:
                    net(x)
                finally:
                    _trace_depth.depth -= 1
        self._all_params = OrderedDict(sorted(net.collect_params().items()))
        for name, p in self._all_params.items():
            if p._data is None:
                raise MXNetError(
                    "parameter %s is not initialized (run net.initialize() "
                    "before the first step)" % name)
        self._train_names = [n for n, p in self._all_params.items()
                             if p.grad_req != "null"]
        self._aux_names = [n for n, p in self._all_params.items()
                           if p.grad_req == "null"]
        self._indices = [tr._param2idx[n] for n in self._train_names]

        o = tr._optimizer
        self._built_opt = o
        # the guard compiles INTO the program, so the flag is read once
        # at build time (toggling the env later needs a fresh step fn)
        self._guard = bool(_config().get("MXT_SKIP_NONFINITE"))
        guard = self._guard
        # the health stat row and the grad_spike chaos rule compile INTO
        # the program too (same read-at-build contract as the guard)
        from .. import health as _health
        from .. import resilience as _resilience

        self._health = _health.enabled()
        health = self._health
        self._spike = _resilience.fault_point().rule("grad_spike") \
            is not None
        spike = self._spike
        upds = [_FusedUpdate._param_update(o, i) for i in self._indices]
        all_params = self._all_params
        train_names, aux_names = self._train_names, self._aux_names
        loss_fn = self._loss_fn

        def pure_loss(train_vals, aux_vals, xv, yv, key):
            """Forward + loss as a pure function of the param pytree; aux
            rebinds (BatchNorm running stats) captured via the CachedOp
            protocol (block.py — _build_cached)."""
            wrappers = {}
            for n, v in zip(train_names, train_vals):
                wrappers[n] = NDArray(v)
            for n, v in zip(aux_names, aux_vals):
                wrappers[n] = NDArray(v)
            mapping = {all_params[n]: w for n, w in wrappers.items()}
            _trace_depth.depth += 1
            try:
                with ag.pause(train_mode=True), _random.key_scope(key), \
                        param_trace_scope(mapping):
                    out = Block.__call__(net, NDArray(xv))
                    outs = list(out) if isinstance(out, (list, tuple)) \
                        else [out]
                    loss = loss_fn(outs[0] if len(outs) == 1 else outs,
                                   NDArray(yv))
            finally:
                _trace_depth.depth -= 1
            new_aux = tuple(jax.lax.stop_gradient(wrappers[n].data)
                            for n in aux_names)
            out_datas = tuple(jax.lax.stop_gradient(o_.data)
                              for o_ in outs)
            # grad of the SUM == the implicit all-ones seed loss.backward()
            # uses; rescale_grad (1/batch) is applied inside the update
            return loss.data.sum(), (loss.data, new_aux, out_datas)

        if not guard:
            def step(train_vals, states, aux_vals, xv, yv, base_key, t, lr,
                     wd, rescale, spike_scale=1.0):
                # per-step key derived on device: no host-side split launch
                key = jax.random.fold_in(base_key, t)
                (_, (loss_vec, new_aux, outs)), grads = jax.value_and_grad(
                    pure_loss, has_aux=True)(train_vals, aux_vals, xv, yv,
                                             key)
                if spike:
                    # seeded chaos: ONE layer's gradient scaled on device
                    # (scale is 1.0 on every non-firing step)
                    grads = _health.apply_grad_spike(grads, train_names,
                                                     spike_scale)
                new_train, new_states = [], []
                for f, w, g, s in zip(upds, train_vals, grads, states):
                    w2, s2 = f(w, g, s, t, lr, wd, rescale)
                    new_train.append(w2)
                    new_states.append(s2)
                if health:
                    # per-layer stats packed INSIDE the program — staged
                    # into the window, never read per step
                    row = _health.stat_row(loss_vec, grads, train_vals,
                                           new_train)
                    return (loss_vec, tuple(new_train),
                            tuple(new_states), new_aux, outs, row)
                return (loss_vec, tuple(new_train), tuple(new_states),
                        new_aux, outs)
        else:
            # non-finite step guard (resilience.py): the all-finite check
            # and the identity-on-overflow update are part of THIS program
            # — zero extra launches. The step count t is CARRIED on device
            # (advances only when the step applied) and the flag lands in
            # a carried bitmask (newest step = bit 0) instead of being
            # read back per step: the engine's in-flight window reads the
            # mask once per K steps and replays the bits into host
            # bookkeeping. aux (BatchNorm stats) also roll back so a NaN
            # forward never pollutes the running statistics.
            def step(train_vals, states, aux_vals, xv, yv, base_key, t,
                     mask, lr, wd, rescale, spike_scale=1.0):
                import jax.numpy as jnp

                t_upd = t + 1  # the count this update applies at
                key = jax.random.fold_in(base_key, t_upd)
                (_, (loss_vec, new_aux, outs)), grads = jax.value_and_grad(
                    pure_loss, has_aux=True)(train_vals, aux_vals, xv, yv,
                                             key)
                if spike:
                    # seeded chaos: ONE layer's gradient scaled on device
                    # (scale is 1.0 on every non-firing step)
                    grads = _health.apply_grad_spike(grads, train_names,
                                                     spike_scale)

                def _apply(_):
                    new_train, new_states = [], []
                    for f, w, g, s in zip(upds, train_vals, grads, states):
                        w2, s2 = f(w, g, s, t_upd, lr, wd, rescale)
                        new_train.append(w2)
                        new_states.append(s2)
                    return tuple(new_train), tuple(new_states), new_aux

                def _skip(_):
                    return (tuple(train_vals), tuple(states),
                            tuple(aux_vals))

                finite = jnp.bool_(True)
                for g in grads:
                    finite = jnp.logical_and(finite, jnp.isfinite(g).all())
                new_train, new_states, kept_aux = jax.lax.cond(
                    finite, _apply, _skip, None)
                t_new = t + jnp.where(finite, 1, 0)
                mask_new = (mask << 1) | jnp.where(finite, 0, 1)
                if health:
                    # the guard bit rides the row's last column, so one
                    # stacked read retires flags AND stats together
                    row = _health.stat_row(loss_vec, grads, train_vals,
                                           new_train, mask=mask_new)
                    return (loss_vec, new_train, new_states, kept_aux,
                            outs, t_new, mask_new, row)
                return (loss_vec, new_train, new_states, kept_aux, outs,
                        t_new, mask_new)

        # weights + optimizer state + aux donated: buffers are reused
        # across steps (the static_alloc analog) and the Parameter
        # wrappers rebind to the outputs
        self._jit = jax.jit(step, donate_argnums=(0, 1, 2))
        from .. import engine, tuning
        if health:
            # stats ride the window's value channel: in guard mode the
            # row's last column carries the guard bit, so the SAME one
            # deferred read per K steps retires flags and stats together
            self._health_mon = _health.HealthMonitor(
                self._train_names, stream="fused_step",
                guard_hook=(lambda: self._consume_flag(False))
                if guard else None)
            on_values = self._consume_health_row
            on_flags = None
        else:
            on_values = None
            on_flags = self._consume_flag if guard else None
        self._stream = engine.StepStream(
            name="fused_step", on_flags=on_flags, on_values=on_values)
        tuning.register_step(self)  # bare tuning.warmup() AOT-compiles us

    # -- per-step host path ------------------------------------------------
    def _consume_flag(self, finite):
        """Land ONE step's deferred guard flag into host bookkeeping —
        called from the engine window's retirement (in dispatch order),
        possibly several steps after the launch."""
        o = self._built_opt
        if finite:
            for i in self._indices:
                o._update_count(i)
        else:
            from .. import resilience
            resilience.record_skipped_step()
        scaler = getattr(self._trainer, "_amp_scaler", None)
        if scaler is not None:
            # dynamic loss-scale backoff driven from the same flag,
            # consumed from the trailing window
            scaler.update_scale(not finite)

    def _consume_health_row(self, step_no, row):
        """Land ONE retired step's stat row (and, in guard mode, its
        guard bit — packed as the row's last column so the stacked
        window read covers both) into host bookkeeping."""
        if self._guard:
            # bit 0 of the step's mask rode the row as 0.0/1.0 exactly
            self._consume_flag(float(row[-1]) == 0.0)  # sync-ok: retired host row
        if self._health_mon is not None:
            self._health_mon.consume(step_no, row)

    def _reset_async(self):
        """Land every deferred flag and drop the device-carried step
        count; the next fused step re-derives it from host counts. Called
        before any path that advances host counts outside the stream."""
        if self._stream is not None and self._stream.pending:
            self._stream.flush()
        self._t_dev = None
        self._mask_dev = None

    def _host_hypers(self, o):
        """(lr, wd) as host floats, cached between steps — with no
        scheduler they only change when the user assigns them, so the
        per-step float() conversions stay off the dispatch hot path."""
        cache = self._hyper_cache
        if cache is None or cache[0] != o.lr or cache[1] != o.wd:
            cache = (o.lr, o.wd, float(o.lr), float(o.wd))  # sync-ok: host scalars, cached
            self._hyper_cache = cache
        return cache[2], cache[3]

    def _sig_entry(self):
        """Tuning-table signature key for this step's net (stable across
        processes: gluon name prefixes are deterministic)."""
        return "fused_step:%s" % self._net.name

    def _record_signature(self, x, y):
        """Remember the batch signature so tuning.warmup() in a resumed
        process can AOT-compile this exact program before the first real
        step."""
        if self._sig_recorded:
            return
        self._sig_recorded = True
        try:
            from .. import tuning

            tuning.record_signature(self._sig_entry(), {
                "x_shape": list(x.shape), "x_dtype": str(x.data.dtype),
                "y_shape": list(y.shape), "y_dtype": str(y.data.dtype),
                "guard": bool(self._guard)})
        except Exception:  # noqa: BLE001 — bookkeeping must not fail a step
            pass

    def aot_warmup(self, x=None, y=None):
        """AOT-lower-and-compile the fused step program without running
        a step (donation makes execute-to-warm destructive — weights are
        never touched). ``x``/``y`` give the batch signature explicitly;
        omitted, the signatures a previous process recorded in the
        tuning table are replayed. With ``MXT_COMPILE_CACHE_DIR`` set
        the compile lands in (warm: replays from) the persistent cache,
        so the first real step performs zero hot-path JIT. Returns the
        number of programs compiled, or False if the step cannot build
        (ineligible config / no recorded signature)."""
        from .. import tuning

        tr = self._trainer
        if not tr._kv_initialized:
            tr._init_kvstore()
        if tr._params_to_init:
            tr._init_params()
        if x is not None:
            if not isinstance(x, NDArray):
                x = _nd.array(x)
            if not isinstance(y, NDArray):
                y = _nd.array(y)
            specs = [{"x_shape": list(x.shape),
                      "x_dtype": str(x.data.dtype),
                      "y_shape": list(y.shape),
                      "y_dtype": str(y.data.dtype)}]
            # persist the signature: a bare tuning.warmup() (this
            # process or the next one) can then replay this compile
            tuning.record_signature(self._sig_entry(), specs[0])
        else:
            specs = tuning.signatures(self._sig_entry())
        if not specs:
            return False
        if self._jit is None and self._fallback_reason is None:
            self._fallback_reason = self.eligible(tr, self._net)
            if self._fallback_reason is None:
                spec = specs[0]
                self._build(_nd.zeros(tuple(spec["x_shape"]),
                                      dtype=spec["x_dtype"]))
        if self._jit is None:
            return False
        o = tr._optimizer
        updater = tr._updaters[0]
        for n, i in zip(self._train_names, self._indices):
            if i not in updater.states:
                updater.states[i] = o.create_state_multi_precision(
                    i, self._all_params[n].data())
                updater.states_synced[i] = True

        def sds(a):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        ws = tuple(sds(self._all_params[n].data().data)
                   for n in self._train_names)
        ss = tuple(tuple(sds(l.data)
                         for l in _FusedUpdate._leaves(updater.states[i]))
                   for i in self._indices)
        aux = tuple(sds(self._all_params[n].data().data)
                    for n in self._aux_names)
        if self._base_key is None:
            self._base_key = _random.new_key()
        import jax.numpy as jnp

        count = 0
        for spec in specs:
            xs = jax.ShapeDtypeStruct(tuple(spec["x_shape"]),
                                      spec["x_dtype"])
            ys = jax.ShapeDtypeStruct(tuple(spec["y_shape"]),
                                      spec["y_dtype"])
            # scalar args mirror the hot path's aval kinds (python
            # int/float = weak-typed; guard t/mask are strong i32/u32)
            # so the persistent-cache key matches the real dispatch
            if self._guard:
                self._jit.lower(ws, ss, aux, xs, ys, self._base_key,
                                jnp.int32(0), jnp.uint32(0), 0.0, 0.0,
                                1.0).compile()
            else:
                self._jit.lower(ws, ss, aux, xs, ys, self._base_key, 1,
                                0.0, 0.0, 1.0).compile()
            count += 1
        return count

    def _publish_hbm(self, updater):
        """Register this step's device working set in the diagnostics
        HBM ledger (once; host arithmetic on shape metadata only): the
        params pool (trainable + aux) and the optimizer-state pool."""
        if self._hbm_published:
            return
        self._hbm_published = True
        try:
            from .. import diagnostics

            params = sum(self._all_params[n].data().data.nbytes
                         for n in self._all_params)
            opt = sum(l.data.nbytes
                      for i in self._indices
                      for l in _FusedUpdate._leaves(updater.states[i]))
            key = self._sig_entry()
            diagnostics.hbm_set("params", key, params)
            diagnostics.hbm_set("optimizer", key, opt)
        except Exception:  # noqa: BLE001 — accounting must not fail a step
            pass

    def _fused_step(self, x, y, batch_size):
        """One fused launch, dispatched asynchronously. Returns None if
        host-side invariants don't hold this step (caller falls back to
        the eager loop)."""
        _t0 = time.perf_counter()  # dispatch-phase span (host work only)
        self._record_signature(x, y)
        tr = self._trainer
        o = tr._optimizer
        updater = tr._updaters[0]
        for n, i in zip(self._train_names, self._indices):
            if i not in updater.states:
                updater.states[i] = o.create_state_multi_precision(
                    i, self._all_params[n].data())
                updater.states_synced[i] = True
        self._publish_hbm(updater)
        # the fused program uses ONE step count for every parameter; if a
        # prior eager/kvstore path left counts uneven, stay eager
        counts = {o._index_update_count.get(i, o.begin_num_update)
                  for i in self._indices}
        if len(counts) > 1:
            self._reset_async()
            return None
        rescale = tr._scale / batch_size
        tr._check_and_rescale_grad(rescale)
        sched = o.lr_scheduler
        if self._guard:
            if sched is not None:
                # scheduler lr depends on the data-dependent step count:
                # observe the flag per step (window forced to 1). t enters
                # as the last APPLIED count; the program bumps it itself.
                base = o._index_update_count.get(
                    self._indices[0], o.begin_num_update) \
                    if self._indices else 0
                num_update = max(o.num_update, base + 1)
                lr = float(sched(num_update))  # sync-ok: host scheduler scalar
                wd = float(o.wd)  # sync-ok: host scalar
                t_in, mask_in = base, 0
            else:
                lr, wd = self._host_hypers(o)
                if self._t_dev is None:
                    import jax.numpy as jnp

                    base = o._index_update_count.get(
                        self._indices[0], o.begin_num_update) \
                        if self._indices else 0
                    self._t_dev = jnp.int32(base)
                    self._mask_dev = jnp.uint32(0)
                t_in, mask_in = self._t_dev, self._mask_dev
        else:
            # host bookkeeping mirrors the eager order (_update_count then
            # _get_lr): the scheduler sees the post-bump num_update
            for i in self._indices:
                o._update_count(i)
            t_in = o._index_update_count[self._indices[0]] \
                if self._indices else 1
            if sched is not None:
                lr = float(sched(o.num_update))  # sync-ok: host scheduler scalar
                wd = float(o.wd)  # sync-ok: host scalar
            else:
                lr, wd = self._host_hypers(o)
        ws = tuple(self._all_params[n].data().data
                   for n in self._train_names)
        ss = tuple(tuple(l.data
                         for l in _FusedUpdate._leaves(updater.states[i]))
                   for i in self._indices)
        aux = tuple(self._all_params[n].data().data
                    for n in self._aux_names)
        if self._base_key is None:
            # drawn lazily so mx.random.seed() between construction and
            # the first step still takes effect
            self._base_key = _random.new_key()
        # seeded chaos: scale is 1.0 except on the one firing dispatch
        # (jit sees the same weak-float aval either way — no retrace)
        spike_scale = 1.0
        if self._spike:
            from .. import health as _health
            spike_scale = _health.grad_spike_scale(
                self._stream._dispatched + 1)
        row = None
        try:
            if self._guard:
                if self._health:
                    (loss_vec, new_w, new_s, new_aux, outs, t_new,
                     mask_new, row) = self._jit(
                        ws, ss, aux, x.data, y.data, self._base_key, t_in,
                        mask_in, lr, wd, rescale, spike_scale)
                else:
                    (loss_vec, new_w, new_s, new_aux, outs, t_new,
                     mask_new) = self._jit(
                        ws, ss, aux, x.data, y.data, self._base_key, t_in,
                        mask_in, lr, wd, rescale, spike_scale)
            elif self._health:
                loss_vec, new_w, new_s, new_aux, outs, row = self._jit(
                    ws, ss, aux, x.data, y.data, self._base_key, t_in, lr,
                    wd, rescale, spike_scale)
            else:
                loss_vec, new_w, new_s, new_aux, outs = self._jit(
                    ws, ss, aux, x.data, y.data, self._base_key, t_in, lr,
                    wd, rescale, spike_scale)
        except Exception as e:  # noqa: BLE001 — OOM gets the HBM ledger
            from .. import diagnostics

            diagnostics.reraise_if_oom(e, "fused_step")
            raise
        _count_launch()
        # rebind unconditionally: donation consumed the input buffers, and
        # on a skipped step the outputs ARE the (identity) old values
        for n, i, w2, s2 in zip(self._train_names, self._indices, new_w,
                                new_s):
            self._all_params[n].data()._set_data(w2)
            for leaf, v in zip(_FusedUpdate._leaves(updater.states[i]), s2):
                leaf._set_data(v)
        for n, v in zip(self._aux_names, new_aux):
            self._all_params[n].data()._set_data(v)
        if self._guard:
            if sched is not None:
                from ..ndarray.pending import PendingValue

                if row is not None:
                    # same single read as the mask path: the row carries
                    # the guard bit in its last column plus the stats
                    r = PendingValue(row).get()  # sync-ok: scheduler forces per-step observe
                    self._consume_health_row(int(t_in) + 1, r)
                else:
                    ok = (int(PendingValue(mask_new).get()) & 1) == 0
                    self._consume_flag(ok)
            else:
                # deferred: the flag lands when the engine window retires
                # this step's token (<= 1 host read per K steps)
                self._t_dev, self._mask_dev = t_new, mask_new
                if row is not None:
                    self._stream.push(loss_vec, value=row)
                else:
                    self._stream.push(loss_vec, flags=mask_new)
        elif row is not None:
            # stats stage into the window; the retirement read the token
            # already costs covers them (bit-equal syncs/step vs off)
            self._stream.push(loss_vec, value=row)
        else:
            # no host-consumed outputs; the token still throttles dispatch
            self._stream.push(loss_vec)
        from .. import telemetry
        telemetry.record_phase("dispatch", time.perf_counter() - _t0,
                               stream="fused_step",
                               step=self._stream._dispatched)
        loss = NDArray(loss_vec)
        if self._return_outputs:
            out_nds = [NDArray(o_) for o_ in outs]
            return loss, out_nds[0] if len(out_nds) == 1 else out_nds
        return loss

    def _eager_step(self, x, y, batch_size):
        """The canonical loop, verbatim — identical numerics, more
        launches."""
        with ag.record():
            out = self._net(x)
            outs = out if not isinstance(out, (list, tuple)) else \
                (out[0] if len(out) == 1 else list(out))
            loss = self._loss_fn(outs, y)
        loss.backward()
        self._trainer.step(batch_size)
        if self._return_outputs:
            return loss, outs
        return loss

    def __call__(self, x, y, batch_size=None):
        if not isinstance(x, NDArray):
            x = _nd.array(x)
        if not isinstance(y, NDArray):
            y = _nd.array(y)
        if batch_size is None:
            batch_size = x.shape[self._batch_axis]
        tr = self._trainer
        if not tr._kv_initialized:
            tr._init_kvstore()
        if tr._params_to_init:
            tr._init_params()
        if self._jit is not None and tr._optimizer is not self._built_opt:
            # trainer.load_states swapped the optimizer object; the jit
            # closed over the old hyper-params — rebuild against the live
            # one so a resumed run stays fused with the right settings
            self._reset_async()
            self._jit = None
            self._fallback_reason = None
            self._hyper_cache = None
        if self._jit is None and self._fallback_reason is None:
            self._fallback_reason = self.eligible(tr, self._net)
            if self._fallback_reason is None:
                self._build(x)
        if self._jit is not None:
            result = self._fused_step(x, y, batch_size)
            if result is not None:
                return result
        return self._eager_step(x, y, batch_size)


def train_step(net, loss_fn, trainer, batch_axis=0, return_outputs=False):
    """Build a fused (one donated launch) training step for ``net``, with
    transparent fallback to the eager record/backward/step loop — the
    functional spelling of ``trainer.fuse_step(net, loss_fn)``."""
    return CachedTrainStep(net, loss_fn, trainer, batch_axis=batch_axis,
                           return_outputs=return_outputs)


class FusedApply:
    """Fuse a list of per-index optimizer updates into ONE donated launch.

    The _FusedUpdate jit brought to any (weights, grads) list keyed by
    updater indices — Module.update's per-parameter loop rides this so the
    symbolic path's optimizer phase is one launch too, sharing
    ``_FusedUpdate._param_update`` for numerics (identical to the eager
    ``Updater`` call, fewer launches). Falls back (returns False) when a
    per-step invariant doesn't hold; the caller then runs the eager loop.
    """

    def __init__(self, optimizer, indices):
        self._opt = optimizer
        self._indices = list(indices)
        self._hyper_cache = None  # (lr, wd, rescale) -> host floats
        upds = [_FusedUpdate._param_update(optimizer, i)
                for i in self._indices]

        def step(ws, gs, ss, t, lr, wd, rescale):
            out_w, out_s = [], []
            for f, w, g, s in zip(upds, ws, gs, ss):
                w2, s2 = f(w, g, s, t, lr, wd, rescale)
                out_w.append(w2)
                out_s.append(s2)
            return tuple(out_w), tuple(out_s)

        self._jit = jax.jit(step, donate_argnums=(0, 2))

    @staticmethod
    def supported(optimizer):
        """Static (per-optimizer) half of the eligibility check; dense
        grads are re-checked per call."""
        return (_config().get("MXT_FUSED_STEP")
                and type(optimizer).__name__ in _FusedUpdate._SUPPORTED
                and type(optimizer).__module__ == opt.Optimizer.__module__
                and not getattr(optimizer, "multi_precision", False)
                and not getattr(optimizer, "aggregate_num", 0))

    def __call__(self, updater, weights, grads):
        o = self._opt
        for i, w, g in zip(self._indices, weights, grads):
            if getattr(g, "stype", "default") != "default":
                return False
            if i not in updater.states:
                updater.states[i] = o.create_state_multi_precision(i, w)
                updater.states_synced[i] = True
        counts = {o._index_update_count.get(i, o.begin_num_update)
                  for i in self._indices}
        if len(counts) > 1:
            return False
        for i in self._indices:
            o._update_count(i)
        t = o._index_update_count[self._indices[0]] if self._indices else 1
        if o.lr_scheduler is not None:
            lr = float(o.lr_scheduler(o.num_update))  # sync-ok: host scheduler scalar
            wd = float(o.wd)  # sync-ok: host scalar
            rs = float(o.rescale_grad)  # sync-ok: host scalar
        else:
            # constant scheduler: hoist the per-step float() conversions
            # off the dispatch hot path (cached until the user changes
            # the hyper-params)
            cache = self._hyper_cache
            if cache is None or cache[0] != o.lr or cache[1] != o.wd or \
                    cache[2] != o.rescale_grad:
                cache = (o.lr, o.wd, o.rescale_grad,  # sync-ok: host scalars, cached
                         float(o.lr), float(o.wd),  # sync-ok: host scalars, cached
                         float(o.rescale_grad))  # sync-ok: host scalars, cached
                self._hyper_cache = cache
            lr, wd, rs = cache[3], cache[4], cache[5]
        ws = tuple(w.data for w in weights)
        gs = tuple(g.data for g in grads)
        ss = tuple(tuple(l.data
                         for l in _FusedUpdate._leaves(updater.states[i]))
                   for i in self._indices)
        new_w, new_s = self._jit(ws, gs, ss, t, lr, wd, rs)
        _count_launch()
        for w, i, w2, s2 in zip(weights, self._indices, new_w, new_s):
            w._set_data(w2)
            for leaf, v in zip(_FusedUpdate._leaves(updater.states[i]), s2):
                leaf._set_data(v)
        return True
