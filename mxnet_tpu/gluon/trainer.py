"""gluon.Trainer (ref: python/mxnet/gluon/trainer.py).

Applies an Optimizer to a set of Parameters. The reference's per-GPU grad
arrays + kvstore allreduce collapse here: each Parameter holds ONE buffer
(possibly sharded over the mesh, in which case the backward pass already
psum-reduced the gradient over ICI). The kvstore path is kept with the same
`update_on_kvstore` decision logic (ref: trainer.py — _init_kvstore,
model.py — _create_kvstore) so KVStore-driven training (including
dist types and server-side optimizers) behaves like the reference.
"""
from __future__ import annotations

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .. import optimizer as opt
from .. import kvstore as kvs
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                "got %s." % (type(params),))
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    "got list of %s." % (type(param),))
            self._param2idx[param.name] = i
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_params = {
            "kvstore": kvstore, "update_on_kvstore": update_on_kvstore}
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._params_to_init = []
        self._reset_kvstore()

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params and set(optimizer_params) != {"rescale_grad"}:
                raise ValueError(
                    "optimizer_params must be None if optimizer is an "
                    "instance of Optimizer instead of str")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _reset_kvstore(self):
        if self._kvstore and self._kvstore.type.startswith("dist"):
            raise RuntimeError(
                "Cannot reset distributed KVStore.")
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._params_to_init = [p for p in self._params]

    def _init_kvstore(self):
        config = self._kvstore_params
        kvstore_arg = config["kvstore"]
        update_on_kvstore = config["update_on_kvstore"]
        has_sparse = any(getattr(p, "_grad_stype", "default") != "default"
                         for p in self._params)
        kvstore = None
        if kvstore_arg:
            if isinstance(kvstore_arg, kvs.KVStore):
                kvstore = kvstore_arg
            elif isinstance(kvstore_arg, str):
                kvstore = kvs.create(kvstore_arg)
            else:
                raise ValueError("kvstore must be a KVStore instance or name")
        elif has_sparse:
            # sparse grads are applied where the weight lives
            kvstore = kvs.create("local")
        if kvstore is not None:
            if has_sparse:
                # ref: trainer.py — sparse gradients force
                # update_on_kvstore=True (row_sparse rows are updated on
                # the store that holds the full weight)
                if update_on_kvstore is False:
                    raise ValueError(
                        "update_on_kvstore=False is not supported with "
                        "sparse gradients (matches reference)")
                update_on_kvstore = True
            if update_on_kvstore is None:
                # reference default: update on kvstore when distributed
                update_on_kvstore = kvstore.type.startswith("dist")
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
                # server-side optimizer owns the state; keep updater list
                # for save_states compatibility
                self._updaters = [kvstore._updater]
        else:
            update_on_kvstore = False
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = True

    def _init_params(self):
        """Lazily register params whose deferred init has completed."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._kvstore is None:
            self._params_to_init = []
            return
        remaining = []
        for param in self._params_to_init:
            if param._deferred_init is not None or param._data is None:
                remaining.append(param)
            else:
                idx = self._param2idx[param.name]
                self._kvstore.init(idx, param.data())
        self._params_to_init = remaining

    @property
    def learning_rate(self):
        return self._optimizer.lr if self._optimizer.lr_scheduler is None \
            else self._optimizer.lr_scheduler(self._optimizer.num_update)

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # ------------------------------------------------------------------
    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce + optimizer update, scaled by 1/batch_size
        (ref: trainer.py — step)."""
        rescale_grad = self._scale / batch_size
        self._check_and_rescale_grad(rescale_grad)
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def _check_and_rescale_grad(self, scale):
        if self._update_on_kvstore and self._kv_initialized and \
                self._optimizer.rescale_grad != scale:
            raise UserWarning(
                "Possible change in the `batch_size` from previous `step` "
                "detected. Optimizer gradient normalizing factor will not "
                "change w.r.t new batch_size when update_on_kvstore=True")
        self._optimizer.rescale_grad = scale

    def allreduce_grads(self):
        """Only reduce gradients, no update (for grad manipulation between
        allreduce and update; ref: trainer.py — allreduce_grads)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        if self._update_on_kvstore:
            raise AssertionError(
                "allreduce_grads() when parameters are updated on kvstore "
                "is not supported. Try setting `update_on_kvstore` to False "
                "when creating trainer.")
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if self._update_on_kvstore:
                # push grad; server applies the update into the weight,
                # pull brings it back
                self._kvstore.push(i, param.list_grad()[0])
                self._kvstore.pull(i, param.data(), ignore_sparse=False)
            else:
                self._kvstore.push(i, param.list_grad()[0])
                self._kvstore.pull(i, param.list_grad()[0])

    def update(self, batch_size, ignore_stale_grad=False):
        """Only the optimizer update (call allreduce_grads first;
        ref: trainer.py — update)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        assert not (self._kvstore and self._update_on_kvstore), \
            "update() when parameters are updated on kvstore is not " \
            "supported. Try setting `update_on_kvstore` to False when " \
            "creating trainer."
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        if self._update_on_kvstore:
            return  # weights already updated server-side in _allreduce_grads
        updater = self._updaters[0]
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if param._data is None:
                if not ignore_stale_grad:
                    raise MXNetError(
                        "parameter %s has not been initialized" % param.name)
                continue
            updater(i, param.grad(), param.data())

    # -- state persistence (ref: trainer.py — save_states/load_states) -----
    def save_states(self, fname):
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            with open(fname, "rb") as f:
                states = f.read()
            for updater in self._updaters:
                updater.set_states(states)
                updater.optimizer = self._updaters[0].optimizer
            self._optimizer = self._updaters[0].optimizer
        param_dict = {i: param for i, param in enumerate(self._params)}
        self._optimizer.param_dict = param_dict
